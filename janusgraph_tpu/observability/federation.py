"""Fleet observability federation: cross-replica telemetry + forensics.

Every observability plane below this module is per-process: one metrics
registry (PR 2), one flight ring (PR 4), one history/SLO engine (PR 13).
After PR 15's serving fleet a failover incident is smeared across N
disjoint rings and N independently-evaluated SLO ladders, and nobody can
answer "what happened to the fleet between 12:03:07 and 12:03:09". This
module is the federation layer that runs IN the fleet frontend process
(`janusgraph_tpu fleet`) next to the router:

- **Federated telemetry** — :class:`FleetFederation.tick` pulls each
  replica's raw history windows (``GET /timeseries?raw=1``, bucket
  vectors included) and merges them into one fleet window per tick with
  fixed per-kind semantics: counter deltas SUM, gauges stay KEYED per
  replica (a gauge has no meaningful cross-process sum), and
  histogram/timer bucket delta vectors ADD element-wise — so the fleet
  window's p50/p95/p99 are *exact to the shared log2 ladder*, bitwise
  equal to recomputing from the concatenated per-replica vectors
  (:func:`merge_series`). A scrape that misses a dead/draining replica
  is served with ``partial: true`` and the missing-replica list — never
  silently complete.

- **Clock-offset estimation** — each scrape is also an NTP-style probe:
  the round-trip is timed on the LOCAL monotonic clock, the reply
  carries the replica's wall ``now``, and the offset estimate is
  ``peer_wall - (local_send_wall + rtt/2)`` with the minimum-RTT sample
  winning (:class:`ClockOffsets`) — the classic filter, good to ~rtt/2.

- **Failover forensics** — :meth:`FleetFederation.incident` pulls every
  replica's flight ring, maps each event's wall ``ts`` onto the
  frontend's clock via the offset estimates, and emits ONE causally
  ordered timeline: a merged event list plus a Chrome-trace document
  with one lane per replica (the PR 13 catapult renderer's vocabulary),
  reconstructing kill -> mark_dead -> re-pin -> warm-up end to end even
  when replica wall clocks disagree by hundreds of milliseconds.

- **Fleet-level SLOs** — the merged fleet windows feed a second PR 13
  burn-rate engine (:class:`~janusgraph_tpu.observability.slo.SLOEngine`
  over :class:`FleetHistory` — same multi-window hysteresis, same
  determinism on a fake clock). Stock specs: fleet availability from
  the summed admission counters, routing health from the router's
  retry/routed counters, and a latency-outlier budget fed by the
  cross-replica detector — a replica whose windowed p99 exceeds
  ``outlier_factor x`` the fleet median raises a ``replica_outlier``
  flight event and burns the ticket-rung outlier budget.

- **Push-mode transport** (PR 20) — behind a negotiated capability
  (``GET /watch/info``; a 404 marks the peer POLL-ONLY and keeps the
  exact scrape path above, byte-compatibly), the frontend opens a
  ``/watch`` stream per replica and receives sealed windows and flight
  events at EVENT latency instead of poll latency.  Pushed windows run
  through the same producer-keyed cursor and the same merge semantics;
  a dropped stream reconnects with its cursors (resume, no duplicates)
  and a cursor gap heals with one full ``raw=1`` re-fetch — the same
  heal the bounded poll tail uses.  Bundle announcements on the stream
  drive **off-host forensics shipping**: the frontend fetches the
  episode (rate-bounded, torn-skip) into a :class:`FleetBundleStore`
  served at ``GET /fleet/bundles``, so a dying replica's forensics
  survive the replica.

Everything remote is bounded (JG208) and runs outside locks (JG203);
every wall-clock subtraction here is offset math over event *stamps*,
marked ``# graphlint: wallclock`` — durations use the monotonic clock
(graphlint JG111, the rule this PR adds).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from janusgraph_tpu.observability.metrics_core import Histogram
from janusgraph_tpu.observability.slo import SLOEngine, SLOSpec

#: flight categories that mark failover phase boundaries, in causal order
_PHASE_EVENTS = (
    ("kill", lambda e: e.get("category") == "fault"
     and e.get("kind") in ("replica_kill", "replica_restart")),
    ("mark_dead", lambda e: e.get("category") == "fleet"
     and e.get("action") == "dead"),
    ("re_pin", lambda e: e.get("category") == "fleet"
     and e.get("action") in ("rejoin", "join")),
    ("warm_up", lambda e: e.get("category") == "fleet"
     and e.get("action") == "warmup"),
    # durable-CDC failover (PR 18): a follower promotes from the log,
    # then proves itself caught up — kill -> promote -> caught_up
    ("promote", lambda e: e.get("category") == "follower_promote"),
    ("caught_up", lambda e: e.get("category") == "cdc_replay"
     and e.get("action") == "caught_up"),
)


# ------------------------------------------------------------------ merging
def merge_series(entries: List[dict]) -> Optional[dict]:
    """Merge per-replica window summaries of ONE timer/histogram metric:
    bucket delta vectors add element-wise, so the merged percentiles are
    the percentiles of the concatenated observation multiset — exact to
    the log2 ladder, by construction bitwise equal to recomputing from
    the concatenated per-replica vectors."""
    entries = [e for e in entries if e and e.get("count")]
    if not entries:
        return None
    width = max(len(e.get("buckets") or []) for e in entries)
    buckets = [0] * width
    count = 0
    total = 0.0
    hi = 0.0
    for e in entries:
        for i, v in enumerate(e.get("buckets") or []):
            buckets[i] += v
        count += int(e["count"])
        total += float(e.get("sum", 0.0))
        hi = max(hi, float(e.get("max", 0.0)))
    return {
        "kind": entries[0].get("kind", "timer"),
        "count": count,
        "sum": total,
        "max": hi,
        "buckets": buckets,
        "p50": Histogram.percentile_of(buckets, 0.50, hi),
        "p95": Histogram.percentile_of(buckets, 0.95, hi),
        "p99": Histogram.percentile_of(buckets, 0.99, hi),
    }


def merge_windows(replica_windows: Dict[str, List[dict]]) -> dict:
    """Merge each replica's NEW history windows into one fleet-window
    body: ``counters`` sum, ``series`` bucket-add (:func:`merge_series`),
    ``gauges`` keyed per replica (last value wins within one scrape), and
    ``by_replica`` keeps each replica's own merged series so the outlier
    detector can compare per-replica percentiles against the fleet."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, dict] = {}
    per_metric: Dict[str, List[dict]] = {}
    by_replica: Dict[str, Dict[str, dict]] = {}
    for replica in sorted(replica_windows):
        ws = replica_windows[replica]
        mine: Dict[str, List[dict]] = {}
        for w in ws:
            for name, delta in (w.get("counters") or {}).items():
                counters[name] = counters.get(name, 0) + int(delta)
            for name, entry in (w.get("series") or {}).items():
                per_metric.setdefault(name, []).append(entry)
                mine.setdefault(name, []).append(entry)
            for name, value in (w.get("gauges") or {}).items():
                gauges.setdefault(name, {})[replica] = value
        for name, entries in mine.items():
            merged = merge_series(entries)
            if merged is not None:
                by_replica.setdefault(name, {})[replica] = merged
    series = {}
    for name, entries in per_metric.items():
        merged = merge_series(entries)
        if merged is not None:
            series[name] = merged
    return {
        "counters": counters,
        "series": series,
        "gauges": gauges,
        "by_replica": by_replica,
    }


# -------------------------------------------------------------- clock offsets
class ClockOffsets:
    """Per-replica wall-clock offset estimates from scrape round-trips.

    One observation per scrape: the caller stamps its wall clock at send,
    times the round-trip on its MONOTONIC clock (a wall-clock rtt would
    go negative under NTP steps — JG111's point), and reads the peer's
    wall ``now`` from the reply. The NTP midpoint estimate assumes the
    reply was generated halfway through the round-trip::

        offset = peer_wall - (local_send_wall + rtt / 2)

    so ``peer_ts - offset`` maps a peer event stamp onto the local wall
    clock, good to about rtt/2. The minimum-RTT sample per replica wins
    (least queueing delay = tightest bound), the standard NTP filter."""

    def __init__(self):
        self._lock = threading.Lock()
        #: replica -> {"offset_s", "rtt_s", "samples"}
        self._est: Dict[str, dict] = {}

    def observe(
        self, replica: str, send_wall: float, rtt_s: float,
        peer_wall: float,
    ) -> float:
        """Fold one round-trip observation; returns the current offset."""
        # wall stamps subtracted for OFFSET estimation, not a duration
        # (the rtt itself was measured on the monotonic clock)
        offset = peer_wall - (send_wall + rtt_s / 2.0)  # graphlint: wallclock -- NTP midpoint offset math over wall stamps; the rtt operand is monotonic-measured
        rtt_s = max(0.0, float(rtt_s))
        with self._lock:
            cur = self._est.get(replica)
            if cur is None or rtt_s <= cur["rtt_s"]:
                self._est[replica] = {
                    "offset_s": offset,
                    "rtt_s": rtt_s,
                    "samples": (cur["samples"] if cur else 0) + 1,
                }
            else:
                cur["samples"] += 1
        return self.offset(replica)

    def offset(self, replica: str) -> float:
        with self._lock:
            est = self._est.get(replica)
            return est["offset_s"] if est else 0.0

    def correct(self, replica: str, ts: float) -> float:
        """Map a peer event's wall stamp onto the local wall clock."""
        return ts - self.offset(replica)  # graphlint: wallclock -- offset correction over wall stamps, not a duration

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {r: dict(e) for r, e in self._est.items()}


# ---------------------------------------------------------------- fleet ring
class FleetHistory:
    """Bounded ring of merged fleet windows — the same ``windows()`` /
    ``add_listener()`` surface :class:`MetricsHistory` gives the SLO
    engine, fed by :meth:`FleetFederation.tick` instead of a registry
    sampler, so the fleet burn-rate engine inherits PR 13's determinism
    (drive ticks on a fake clock, get a byte-stable alert sequence)."""

    def __init__(self, capacity: int = 360):
        self._ring: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._listeners: List[Callable[[dict], None]] = []

    def append(self, window: dict) -> None:
        with self._lock:
            self._ring.append(window)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(window)
            except Exception:  # noqa: BLE001 - a listener must not kill the scraper
                pass

    def windows(self, last: int = 0) -> List[dict]:
        with self._lock:
            ws = list(self._ring)
        return ws[-last:] if last > 0 else ws

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)


def fleet_default_specs(
    availability_objective: float = 0.999,
    routing_objective: float = 0.99,
    outlier_objective: float = 0.99,
    fast_windows: int = 3,
    slow_windows: int = 36,
    page_burn: float = 14.4,
    ticket_burn: float = 6.0,
) -> List[SLOSpec]:
    """The stock FLEET spec set (``metrics.fleet-*`` keys):

    - ``fleet_availability`` — the summed admission counters across every
      replica: the fraction of fleet-arriving requests not shed.
    - ``fleet_routing`` — router health: retries-elsewhere (each one a
      failed first attempt) against successfully routed requests.
    - ``fleet_latency_outlier`` — the cross-replica outlier budget:
      federation ticks where some replica's windowed p99 exceeded
      ``outlier_factor x`` the fleet median, against all ticks. Sized so
      a persistent outlier burns the TICKET rung (one sick replica is an
      operator ticket, not a page — the router is already steering
      around it)."""
    common = dict(
        fast_windows=fast_windows, slow_windows=slow_windows,
        page_burn=page_burn, ticket_burn=ticket_burn,
    )
    return [
        SLOSpec(
            name="fleet_availability", kind="availability",
            objective=availability_objective, **common,
        ),
        SLOSpec(
            name="fleet_routing", kind="availability",
            objective=routing_objective,
            good_counter="fleet.router.routed",
            bad_counter="fleet.router.retries", **common,
        ),
        SLOSpec(
            name="fleet_latency_outlier", kind="availability",
            objective=outlier_objective,
            good_counter="fleet.federation.ticks",
            bad_counter="fleet.federation.outlier_windows", **common,
        ),
    ]


def _default_watch_factory(url: str, subscribe: dict, timeout_s: float):
    """Open a real ``/watch`` WebSocket against a replica base URL
    (tests inject a fake factory, the same seam as ``fetch``)."""
    from janusgraph_tpu.driver.client import WatchSession

    return WatchSession(
        url, subscribe=subscribe, connect_timeout_s=timeout_s
    )


# ------------------------------------------------------- bundle shipping
class FleetBundleStore:
    """Fleet-wide retention of per-replica forensics bundles.

    When a replica's BundleWriter announces an episode on its telemetry
    bus (a ``bundle`` flight event on the push stream), the frontend
    fetches the bundle off-host into this bounded ring — so a replica
    that dies seconds later still has its dying forensics readable at
    ``GET /fleet/bundles``.  Rate-bounded per replica
    (``min_interval_s``) and bounded in count (``retention``); an
    unreadable/torn fetch is skipped and counted, never fatal."""

    def __init__(
        self,
        retention: int = 16,
        min_interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.retention = max(1, int(retention))
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.retention)
        self._last_fetch: Dict[str, float] = {}
        self.fetched = 0
        self.skipped = 0

    def should_fetch(self, replica: str) -> bool:
        """Rate bound: at most one fetch per replica per
        ``min_interval_s`` (a flapping pager must not turn the frontend
        into a bundle vacuum)."""
        with self._lock:
            now = self._clock()
            last = self._last_fetch.get(replica)
            if last is not None and now - last < self.min_interval_s:
                self.skipped += 1
                return False
            self._last_fetch[replica] = now
            return True

    def add(
        self, replica: str, reason: str, path: str, bundle: dict
    ) -> None:
        with self._lock:
            self.fetched += 1
            self._ring.append({
                "replica": replica,
                "reason": reason,
                "path": path,
                "ts": bundle.get("ts"),
                "fetched_at": self._wall(),
                "bundle": bundle,
            })

    def summaries(self) -> List[dict]:
        """The ``GET /fleet/bundles`` listing (newest last), bundles
        themselves elided."""
        with self._lock:
            return [
                {k: v for k, v in b.items() if k != "bundle"}
                for b in self._ring
            ]

    def get(self, replica: str = "", index: int = -1) -> Optional[dict]:
        """One retained bundle (full body): the newest, or ``index``
        into the (optionally replica-filtered) retained list."""
        with self._lock:
            items = [
                b for b in self._ring
                if not replica or b["replica"] == replica
            ]
        if not items:
            return None
        try:
            return items[index]
        except IndexError:
            return None

    def status(self) -> dict:
        with self._lock:
            return {
                "retention": self.retention,
                "min_interval_s": self.min_interval_s,
                "retained": len(self._ring),
                "fetched": self.fetched,
                "rate_skipped": self.skipped,
            }


# ------------------------------------------------------------ push channel
class _PushChannel:
    """One replica's live ``/watch`` subscription on the frontend.

    A reader thread drains the stream: windows buffer for the next
    :meth:`FleetFederation.tick` merge (same cursor pipeline as poll),
    flight events feed the freshness timer and bundle shipping the
    moment they arrive — the latency collapse push mode exists for.
    The session object only needs ``recv(timeout) -> frame|None`` and
    ``close()`` (injectable via ``watch_factory`` for tests)."""

    def __init__(self, federation, name: str, url: str, producer: str, session):
        self.federation = federation
        self.name = name
        self.url = url
        self.producer = producer
        self.session = session
        self._lock = threading.Lock()
        self._connected = True
        self._windows: List[dict] = []
        self.events_seen = 0
        self.windows_seen = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="fleet-push-%s" % self.name,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.session.close()
        except Exception:  # noqa: BLE001 - teardown must not raise
            pass
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    @property
    def connected(self) -> bool:
        with self._lock:
            return self._connected

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self.session.recv(timeout=1.0)
                if frame is None:
                    continue
                self._handle(frame)
            except Exception as e:  # noqa: BLE001 - record before dying (JG112)
                if not self._stop.is_set():
                    from janusgraph_tpu.observability.flight import (
                        recorder,
                    )

                    recorder.record(
                        "thread_error",
                        thread="fleet-push-%s" % self.name,
                        error=repr(e),
                    )
                with self._lock:
                    self._connected = False
                return

    def _handle(self, frame: dict) -> None:
        if not isinstance(frame, dict) or frame.get("type") != "event":
            return  # hello / heartbeat
        stream = frame.get("stream")
        data = frame.get("data")
        if not isinstance(data, dict):
            return
        if stream == "window":
            with self._lock:
                self._windows.append(data)
                self.windows_seen += 1
        elif stream == "flight":
            with self._lock:
                self.events_seen += 1
            self.federation._on_push_event(self, data)

    def take_windows(self) -> List[dict]:
        """Drain the buffered windows for this tick's merge."""
        with self._lock:
            ws = self._windows
            self._windows = []
            return ws

    def state(self) -> dict:
        with self._lock:
            return {
                "replica": self.name,
                "producer": self.producer,
                "connected": self._connected,
                "windows_seen": self.windows_seen,
                "events_seen": self.events_seen,
                "buffered": len(self._windows),
            }


# ------------------------------------------------------------- the federator
class FleetFederation:
    """The fleet frontend's scrape-merge-evaluate loop over a
    :class:`~janusgraph_tpu.server.fleet.FleetRouter`'s members.

    ``fetch``, ``clock`` and ``wall_clock`` are injectable and
    :meth:`tick` is synchronous, so the degradation/skew/SLO tests drive
    scrapes deterministically without sockets or threads (the same
    pattern as the router and gossip)."""

    def __init__(
        self,
        router,
        fetch: Optional[Callable[[str, float], dict]] = None,
        interval_s: float = 2.0,
        timeout_s: float = 2.0,
        retention: int = 360,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        outlier_metric: str = "server.request.wall",
        outlier_factor: float = 3.0,
        outlier_min_count: int = 20,
        scrape_window: int = 8,
        slo_specs: Optional[List[SLOSpec]] = None,
        push_enabled: bool = False,
        watch_factory=None,
        ship_bundles: bool = True,
        bundle_retention: int = 16,
        bundle_min_interval_s: float = 5.0,
        watchdog=None,
    ):
        from janusgraph_tpu.server.fleet import _default_fetch

        self.router = router
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._fetch = fetch or _default_fetch
        self._clock = clock
        self._wall = wall_clock
        #: negotiated streaming transport (PR 20): when on, replicas
        #: that answer /watch/info get a push channel; refusals are
        #: poll-only peers on the exact PR 17 scrape path
        self.push_enabled = bool(push_enabled)
        self._watch_factory = watch_factory or _default_watch_factory
        self.ship_bundles = bool(ship_bundles)
        self.bundles = FleetBundleStore(
            retention=bundle_retention,
            min_interval_s=bundle_min_interval_s,
            clock=clock, wall_clock=wall_clock,
        )
        self._watchdog = watchdog
        self._push: Dict[str, _PushChannel] = {}
        self._push_refused: set = set()
        #: per-producer last pushed flight seq (reconnect resume cursor)
        self._flight_seq: Dict[str, int] = {}
        self._tick_count = 0
        self.outlier_metric = outlier_metric
        self.outlier_factor = float(outlier_factor)
        self.outlier_min_count = int(outlier_min_count)
        #: windows requested per post-bootstrap scrape — a margin over
        #: the expected interval_s / producer-interval ratio; too small
        #: shows up as fleet.federation.cursor_gaps
        self.scrape_window = int(scrape_window)
        #: replicas that have answered a full-backlog bootstrap scrape
        self._bootstrapped: set = set()
        self.history = FleetHistory(capacity=retention)
        self.offsets = ClockOffsets()
        self.slo = SLOEngine(
            self.history,
            specs=(
                slo_specs if slo_specs is not None
                else fleet_default_specs()
            ),
        ).install()
        self._lock = threading.Lock()
        self._seq = 0
        #: per-replica last scraped history window seq (scrape cursor)
        self._last_seq: Dict[str, int] = {}
        #: previous cumulative values of the frontend's own fleet.*
        #: counters, merged into fleet windows as the router's lane.
        #: Primed NOW so the first window carries increments since this
        #: federation was created — not whatever the process-global
        #: registry accumulated before it (prior fleets, other tests).
        self._prev_local: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._local_deltas()

    # -------------------------------------------------------------- scraping
    def targets(self) -> Dict[str, dict]:
        """name -> {url, state} for every fleet member. DEAD members are
        listed (they belong in the missing-replica report) but never
        fetched — a crashed replica must not cost one timeout per tick."""
        from janusgraph_tpu.server.fleet import DEAD

        out = {}
        for name, handle in sorted(self.router.replicas().items()):
            out[name] = {
                "url": handle.base_url,
                "skip": handle.state == DEAD,
            }
        return out

    def tick(self) -> dict:
        """One federation round: scrape every live replica's raw history
        windows, estimate clock offsets from the round-trips, merge one
        fleet window (partial + missing list when any replica failed to
        answer), fold in the frontend's own router-plane counters, run
        the outlier detector, append (which drives the fleet SLO
        engine), and account the scrape overhead. Returns the window."""
        from janusgraph_tpu.observability import registry

        t0 = time.perf_counter()
        cpu0 = time.thread_time()
        registry.counter("fleet.federation.ticks").inc()
        missing: List[str] = []
        contributed: Dict[str, List[dict]] = {}
        live: List[tuple] = []
        for name, target in self.targets().items():
            if target["skip"]:
                missing.append(name)
            else:
                live.append((name, target["url"]))
        # push transport first: replicas with a live channel are served
        # from their pushed-window buffer and SKIP the HTTP scrape
        # entirely; everyone else (poll-only peers, refused capability,
        # dropped channels this tick) takes the PR 17 poll path below
        push_served: Dict[str, _PushChannel] = {}
        if self.push_enabled:
            push_served = self._push_tick(live)
            live = [(n, u) for n, u in live if n not in push_served]
        # fetches run in parallel — the tick's wall cost is the slowest
        # replica, not the sum. Each fetch measures its own RTT (offset
        # estimation) on the monotonic clock.
        results: Dict[str, Optional[tuple]] = {}

        def _scrape(name: str, url: str) -> None:
            # after the bootstrap scrape (full backlog) only the recent
            # tail is requested — a full-ring payload per tick is O(n^2)
            # over a run; the cursor-gap counter below catches a tail
            # shorter than the gap since the last successful scrape
            suffix = "/timeseries?raw=1"
            if name in self._bootstrapped:
                suffix += f"&window={self.scrape_window}"
            send_wall = self._wall()
            m0 = self._clock()
            c0 = time.thread_time()
            try:
                payload = self._fetch(url + suffix, self.timeout_s)
            except Exception:  # noqa: BLE001 - any scrape failure = missing
                results[name] = (None, time.thread_time() - c0)
                return
            results[name] = (
                (send_wall, self._clock() - m0, payload),
                time.thread_time() - c0,
            )

        if len(live) == 1:
            _scrape(*live[0])
        elif live:
            threads = [
                threading.Thread(
                    target=_scrape, args=(name, url), daemon=True
                )
                for name, url in live
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=self.timeout_s * 2 + 1.0)
        fetch_cpu_s = 0.0
        for name, url in live:
            got, cpu_s = results.get(name) or (None, 0.0)
            fetch_cpu_s += cpu_s
            payload = got[2] if got else None
            if not isinstance(payload, dict) or "windows" not in payload:
                registry.counter("fleet.federation.scrape_failures").inc()
                missing.append(name)
                continue
            send_wall, rtt_s, _ = got
            self._bootstrapped.add(name)
            peer_wall = payload.get("now")
            if isinstance(peer_wall, (int, float)):
                self.offsets.observe(
                    name, send_wall, rtt_s, float(peer_wall)
                )
            # the scrape cursor keys on the PRODUCER identity the
            # payload reports, not the routing name: an in-process
            # fleet (test/bench harness) serves the same shared history
            # ring from every port, and cursoring per routing name
            # would merge each window once per replica (3x counters).
            # Real fleets with no identity set fall back to the routing
            # name — one producer per process, unchanged semantics.
            producer = str(payload.get("replica") or "") or name
            with self._lock:
                cursor = self._last_seq.get(producer, 0)
            fresh = [
                w for w in payload["windows"]
                if isinstance(w, dict) and int(w.get("seq", 0)) > cursor
            ]
            if fresh:
                if cursor > 0 and int(fresh[0].get("seq", 0)) > cursor + 1:
                    # the bounded tail didn't reach back to the cursor:
                    # heal with ONE full-backlog re-fetch instead of
                    # letting the gap count grow tick after tick
                    registry.counter(
                        "fleet.federation.cursor_gaps"
                    ).inc()
                    healed = self._heal_cursor(url, cursor)
                    if healed:
                        fresh = healed
                with self._lock:
                    self._last_seq[producer] = int(fresh[-1]["seq"])
            contributed[name] = fresh
        # push-served replicas: merge their buffered pushed windows
        # through the SAME producer-keyed cursor (shared producers
        # still count once) and the same gap heal
        for name, channel in sorted(push_served.items()):
            producer = channel.producer
            self._bootstrapped.add(name)
            with self._lock:
                cursor = self._last_seq.get(producer, 0)
            fresh = [
                w for w in channel.take_windows()
                if isinstance(w, dict) and int(w.get("seq", 0)) > cursor
            ]
            if fresh:
                if cursor > 0 and int(fresh[0].get("seq", 0)) > cursor + 1:
                    # the bus dropped oldest under backpressure: same
                    # gap, same heal — the poll path never went away
                    registry.counter(
                        "fleet.federation.cursor_gaps"
                    ).inc()
                    healed = self._heal_cursor(channel.url, cursor)
                    if healed:
                        fresh = healed
                with self._lock:
                    self._last_seq[producer] = int(fresh[-1]["seq"])
            contributed[name] = fresh
        body = merge_windows(contributed)
        # the outlier detector runs BEFORE the local-counter diff so its
        # verdict counter lands in THIS window — the SLO engine then
        # evaluates the window that caused the burn, not the next one
        outliers = self._outlier_check(body["by_replica"])
        for name, delta in self._local_deltas().items():
            body["counters"][name] = (
                body["counters"].get(name, 0) + delta
            )
        partial = bool(missing)
        if partial:
            registry.counter("fleet.federation.partial_scrapes").inc()
        with self._lock:
            self._seq += 1
            window = {
                "seq": self._seq,
                "t": self._clock(),
                "ts": self._wall(),
                "interval_s": self.interval_s,
                "replicas": sorted(contributed),
                "partial": partial,
                "missing": sorted(missing),
                "outliers": outliers,
                **body,
            }
        # two overhead measures: wall (what this tick took end-to-end,
        # queueing included) and CPU (the cost the federation actually
        # imposes on the box — fetch-thread CPU + this thread's merge/
        # evaluate CPU). On an oversubscribed core the wall measures the
        # scheduler, not the scrape; budgets gate on the CPU number.
        overhead_ms = (time.perf_counter() - t0) * 1000.0
        overhead_cpu_ms = (
            (time.thread_time() - cpu0) + fetch_cpu_s
        ) * 1000.0
        registry.set_gauge(
            "fleet.federation.overhead_ms", round(overhead_ms, 4)
        )
        registry.set_gauge(
            "fleet.federation.overhead_cpu_ms",
            round(overhead_cpu_ms, 4),
        )
        registry.timer("fleet.federation.scrape").update(
            int(overhead_ms * 1e6)
        )
        registry.timer("fleet.federation.scrape_cpu").update(
            int(overhead_cpu_ms * 1e6)
        )
        # append last: listeners (the fleet SLO engine) see a window
        # whose overhead accounting is already on the books
        self.history.append(window)
        with self._lock:
            # watchdog progress advances only when a tick COMPLETES —
            # a tick wedged mid-scrape freezes this and fires a stall
            self._tick_count += 1
        return window

    def _heal_cursor(self, url: str, cursor: int) -> Optional[List[dict]]:
        """One full-backlog ``raw=1`` re-fetch after a cursor gap (the
        bounded tail or a drop-oldest push stream didn't reach back to
        the cursor).  Returns the fresh windows past the cursor, or
        None when the heal itself failed (the gap stands, counted
        once — not per tick)."""
        from janusgraph_tpu.observability import registry

        try:
            payload = self._fetch(url + "/timeseries?raw=1", self.timeout_s)
        except Exception:  # noqa: BLE001 - a failed heal is a counted no-op
            registry.counter("fleet.federation.cursor_heal_failures").inc()
            return None
        if not isinstance(payload, dict) or "windows" not in payload:
            registry.counter("fleet.federation.cursor_heal_failures").inc()
            return None
        registry.counter("fleet.federation.cursor_heals").inc()
        return [
            w for w in payload["windows"]
            if isinstance(w, dict) and int(w.get("seq", 0)) > cursor
        ]

    # ------------------------------------------------------ push transport
    def _push_tick(self, live: List[tuple]) -> Dict[str, _PushChannel]:
        """Maintain push channels for this tick: drop dead streams
        (flighted, and renegotiated with resume cursors in the same
        pass), negotiate with replicas not yet refused, and return the
        channels serving this tick."""
        from janusgraph_tpu.observability import flight_recorder, registry

        live_names = {n for n, _ in live}
        for name in list(self._push):
            channel = self._push[name]
            if name not in live_names or not channel.connected:
                channel.stop()
                del self._push[name]
                flight_recorder.record(
                    "fleet", action="push_lost", replica=name
                )
                registry.counter("fleet.federation.push_lost").inc()
        served: Dict[str, _PushChannel] = {}
        for name, url in live:
            channel = self._push.get(name)
            if channel is None and name not in self._push_refused:
                channel = self._open_push(name, url)
            if channel is not None:
                served[name] = channel
        registry.set_gauge(
            "fleet.federation.push_channels", float(len(served))
        )
        return served

    def _open_push(self, name: str, url: str) -> Optional[_PushChannel]:
        """Negotiate the streaming capability with one replica and open
        its push channel.  A capability miss (a REPLY without the
        ``watch`` bit — a PR 17 peer's 404 body) marks the peer
        POLL-ONLY, terminally: the feature-bit discipline keeps it on
        the exact PR 17 scrape path from here on.  A transport failure
        (connection refused, timeout — the probe never got an answer)
        is NOT a refusal: a replica mid-restart must renegotiate when
        it comes back, so it retries next tick."""
        from janusgraph_tpu.observability import flight_recorder, registry

        send_wall = self._wall()
        m0 = self._clock()
        try:
            info = self._fetch(url + "/watch/info", self.timeout_s)
        except Exception:  # noqa: BLE001 - unanswered probe: retry next tick
            registry.counter(
                "fleet.federation.push_connect_failures"
            ).inc()
            return None
        if not isinstance(info, dict) or not info.get("watch"):
            self._push_refused.add(name)
            registry.counter("fleet.federation.push_refused").inc()
            return None
        rtt_s = self._clock() - m0
        peer_wall = info.get("now")
        if isinstance(peer_wall, (int, float)):
            # the negotiation round-trip doubles as the NTP-style
            # offset probe the poll path gets from every scrape
            self.offsets.observe(name, send_wall, rtt_s, float(peer_wall))
        producer = str(info.get("replica") or "") or name
        with self._lock:
            cursors = {"window": int(self._last_seq.get(producer, 0))}
            flight_cursor = self._flight_seq.get(producer)
        if flight_cursor is not None:
            # reconnect: resume the flight stream past what we saw
            cursors["flight"] = int(flight_cursor)
        subscribe = {
            "streams": ["window", "flight"],
            "cursors": cursors,
            "heartbeat_s": max(0.5, min(self.interval_s, 2.0)),
            "name": "fleet-federation",
        }
        try:
            session = self._watch_factory(url, subscribe, self.timeout_s)
        except Exception:  # noqa: BLE001 - transport failure: retry next tick
            registry.counter(
                "fleet.federation.push_connect_failures"
            ).inc()
            return None
        channel = _PushChannel(self, name, url, producer, session)
        channel.start()
        self._push[name] = channel
        registry.counter("fleet.federation.push_negotiated").inc()
        flight_recorder.record(
            "fleet", action="push_on", replica=name, producer=producer
        )
        return channel

    def _on_push_event(self, channel: _PushChannel, event: dict) -> None:
        """A flight event arrived on a push stream (reader thread):
        advance the resume cursor, account the event→frontend freshness
        lag, and ship announced bundles off-host."""
        from janusgraph_tpu.observability import registry

        producer = str(event.get("replica") or "") or channel.producer
        seq = int(event.get("seq", 0))
        with self._lock:
            if seq > self._flight_seq.get(producer, 0):
                self._flight_seq[producer] = seq
        ts = event.get("ts")
        if isinstance(ts, (int, float)):
            lag_s = self._wall() - self.offsets.correct(producer, float(ts))  # graphlint: wallclock -- freshness lag over offset-corrected stamps; the quantity push mode exists to shrink
            registry.timer("fleet.federation.push_event_lag").update(
                int(max(0.0, lag_s) * 1e9)
            )
        if str(event.get("category", "")) == "bundle":
            self._ship_bundle(channel, event)

    def _ship_bundle(self, channel: _PushChannel, event: dict) -> None:
        """Fetch an announced forensics bundle off-host (rate-bounded
        per replica, torn/unparseable fetches skipped and counted)."""
        from janusgraph_tpu.observability import registry

        if not self.ship_bundles:
            return
        replica = str(event.get("replica") or "") or channel.producer
        if not self.bundles.should_fetch(replica):
            registry.counter("fleet.federation.bundle_rate_limited").inc()
            return
        try:
            payload = self._fetch(
                channel.url + "/debug/bundle", self.timeout_s
            )
        except Exception:  # noqa: BLE001 - a lost bundle is counted, not fatal
            payload = None
        # GET /debug/bundle returns the bundle dict DIRECTLY (with its
        # on-disk "path" folded in) — a 404/error body carries "status"
        # instead, and a torn reply is not a dict at all
        bundle = (
            payload
            if isinstance(payload, dict) and "status" not in payload
            else None
        )
        if not isinstance(bundle, dict) or not bundle:
            # torn-skip: the replica had no readable bundle (or died
            # mid-reply) — skip it, never poison the store
            registry.counter(
                "fleet.federation.bundle_fetch_failures"
            ).inc()
            return
        self.bundles.add(
            replica,
            reason=str(event.get("reason") or ""),
            path=str(event.get("path") or ""),
            bundle=bundle,
        )
        registry.counter("fleet.federation.bundles_shipped").inc()

    def push_status(self) -> dict:
        """The push-transport block of ``GET /fleet/timeseries`` and
        the CLI's fleet view."""
        with self._lock:
            refused = sorted(self._push_refused)
            channels = {n: c.state() for n, c in self._push.items()}
        return {
            "enabled": self.push_enabled,
            "channels": channels,
            "poll_only": refused,
            "bundles": self.bundles.status(),
        }

    def _tick_progress(self) -> dict:
        """Stall-watchdog progress source (auto-registered by
        :meth:`start`): the loop is active while the thread runs, and
        progress is completed ticks — a tick wedged in a scrape stops
        advancing it and fires a ``stall`` flight event."""
        with self._lock:
            count = self._tick_count
        return {
            "active": 1 if self._thread is not None else 0,
            "progress": count,
        }

    def _local_deltas(self) -> Dict[str, int]:
        """Window deltas of the frontend process's OWN ``fleet.*``
        counters (router retries/deaths, federation verdicts): the
        router's lane of the fleet window — these live here, not on any
        replica, so a pure scrape would never see them."""
        from janusgraph_tpu.observability import registry

        counters, _timers, _hists, _gauges = registry.metric_objects()
        out: Dict[str, int] = {}
        with self._lock:
            for name, c in counters.items():
                if not name.startswith("fleet."):
                    continue
                cur = int(c.count)
                prev = self._prev_local.get(name)
                self._prev_local[name] = cur
                delta = (
                    cur - prev if prev is not None and cur >= prev else cur
                )
                if delta:
                    out[name] = delta
        return out

    def _outlier_check(
        self, by_replica: Dict[str, Dict[str, dict]]
    ) -> List[dict]:
        """Cross-replica latency outlier detection: a replica whose
        windowed p99 of the watched metric exceeds ``outlier_factor x``
        the fleet MEDIAN p99 (among replicas with enough observations)
        raises a ``replica_outlier`` flight event and burns the outlier
        budget (``fleet.federation.outlier_windows``)."""
        from janusgraph_tpu.observability import flight_recorder, registry

        entries = by_replica.get(self.outlier_metric) or {}
        p99s = {
            r: float(e["p99"]) for r, e in entries.items()
            if int(e.get("count", 0)) >= self.outlier_min_count
        }
        if len(p99s) < 2:
            return []
        ranked = sorted(p99s.values())
        mid = len(ranked) // 2
        median = (
            ranked[mid] if len(ranked) % 2
            else (ranked[mid - 1] + ranked[mid]) / 2.0
        )
        if median <= 0:
            return []
        outliers = []
        for replica, p99 in sorted(p99s.items()):
            if p99 > self.outlier_factor * median:
                outliers.append({
                    "replica": replica,
                    "p99_ns": p99,
                    "fleet_median_ns": median,
                    "factor": round(p99 / median, 2),
                })
                flight_recorder.record(
                    "replica_outlier",
                    replica=replica, metric=self.outlier_metric,
                    p99_ns=p99, fleet_median_ns=median,
                    threshold_factor=self.outlier_factor,
                )
        if outliers:
            registry.counter("fleet.federation.outlier_windows").inc()
        return outliers

    # ------------------------------------------------------------ lifecycle
    def start(self, interval_s: Optional[float] = None) -> None:
        if interval_s is not None:
            self.interval_s = float(interval_s)
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 - the scraper must not die
                    # record before continuing (JG112): an unrecorded
                    # tick failure looks identical to "no new windows"
                    from janusgraph_tpu.observability.flight import (
                        recorder,
                    )

                    recorder.record(
                        "thread_error", thread="fleet-federation",
                        error=repr(e),
                    )

        self._thread = threading.Thread(
            target=_loop, daemon=True, name="fleet-federation"
        )
        self._thread.start()
        # the tick loop auto-registers as a watchdog progress source
        # (no manual wiring): a wedged tick fires a stall event
        if self._watchdog is None:
            from janusgraph_tpu.observability.continuous import (
                watchdog_singleton,
            )

            self._watchdog = watchdog_singleton()
        self._watchdog.register_progress(
            "fleet.federation.tick", self._tick_progress
        )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.unregister_progress("fleet.federation.tick")
        for name in list(self._push):
            self._push.pop(name).stop()

    # --------------------------------------------------------- merged views
    def timeseries_view(self, name: str = "", window: int = 0) -> dict:
        """The ``GET /fleet/timeseries`` payload: merged fleet windows as
        per-metric series (``?name=`` prefix filter, ``?window=N`` last-N
        bound, same vocabulary as the per-replica ``/timeseries``).
        Counter points carry the fleet-summed ``delta``, series points
        the merged summary WITH its bucket vector, gauge points a
        ``value`` dict keyed per replica. ``partial``/``missing`` report
        scrape completeness over the served slice — a window scraped
        around a dead replica never reads as complete."""
        ws = self.history.windows(window)
        names = set()
        for w in ws:
            names.update(w["counters"])
            names.update(w["series"])
            names.update(w["gauges"])
        series: Dict[str, List[dict]] = {}
        for n in sorted(names):
            if name and not n.startswith(name):
                continue
            pts = []
            for w in ws:
                point = {"seq": w["seq"], "ts": w["ts"]}
                if n in w["counters"]:
                    point["delta"] = w["counters"][n]
                elif n in w["series"]:
                    point.update(w["series"][n])
                elif n in w["gauges"]:
                    point["value"] = w["gauges"][n]
                else:
                    continue
                if w["partial"]:
                    point["partial"] = True
                pts.append(point)
            if pts:
                series[n] = pts
        missing = sorted({m for w in ws for m in w["missing"]})
        return {
            "interval_s": self.interval_s,
            "windows": len(ws),
            "first_seq": ws[0]["seq"] if ws else 0,
            "last_seq": ws[-1]["seq"] if ws else 0,
            "replicas": sorted({r for w in ws for r in w["replicas"]}),
            "partial": bool(missing),
            "missing": missing,
            "offsets": self.offsets.snapshot(),
            "slo": self.slo.snapshot(),
            "series": series,
        }

    def metrics_view(self) -> dict:
        """The ``GET /fleet/metrics`` payload: an on-demand merge of
        every live replica's CURRENT ``/telemetry`` metric snapshot —
        counters sum, gauges keyed per replica, timers/histograms keyed
        per replica with a fleet count/mean roll-up (exact fleet
        percentiles live in the windowed view, where bucket vectors
        exist). Partial + missing semantics match the windowed view."""
        missing: List[str] = []
        snaps: Dict[str, dict] = {}
        for name, target in self.targets().items():
            if target["skip"]:
                missing.append(name)
                continue
            try:
                payload = self._fetch(
                    target["url"] + "/telemetry", self.timeout_s
                )
                snaps[name] = payload["metrics"]
            except Exception:  # noqa: BLE001 - any scrape failure = missing
                missing.append(name)
        merged: Dict[str, dict] = {}
        for replica in sorted(snaps):
            for mname, m in snaps[replica].items():
                kind = m.get("type")
                slot = merged.setdefault(mname, {"type": kind})
                if kind == "counter":
                    slot["count"] = (
                        slot.get("count", 0) + int(m.get("count", 0))
                    )
                elif kind == "gauge":
                    slot.setdefault("value", {})[replica] = m.get("value")
                else:  # timer / histogram: keyed + count/mean roll-up
                    slot.setdefault("by_replica", {})[replica] = m
                    n_old = slot.get("count", 0)
                    n_new = int(m.get("count", 0))
                    slot["count"] = n_old + n_new
                    if kind == "timer":
                        t_old = slot.get("total_ms", 0.0)
                        slot["total_ms"] = t_old + float(
                            m.get("total_ms", 0.0)
                        )
                        slot["mean_ms"] = (
                            slot["total_ms"] / slot["count"]
                            if slot["count"] else 0.0
                        )
        return {
            "replicas": sorted(snaps),
            "partial": bool(missing),
            "missing": sorted(missing),
            "metrics": merged,
        }

    # ------------------------------------------------------------- forensics
    def incident(self, window_s: float = 60.0) -> dict:
        """The ``GET /fleet/incident`` payload: every replica's flight
        ring pulled, every event's wall stamp corrected onto the
        frontend clock by the per-replica offset estimate, merged into
        one causally ordered event list + a Chrome-trace document with a
        lane per replica. ``window_s`` bounds the lookback (0 = whole
        rings). Dead/unreachable replicas make the report ``partial`` —
        the incident ends exactly where their ring went dark, which is
        itself forensic signal."""
        from janusgraph_tpu.observability import flight_recorder

        missing: List[str] = []
        raw: List[dict] = []
        sources: List[str] = []
        for name, target in self.targets().items():
            if target["skip"]:
                missing.append(name)
                continue
            try:
                payload = self._fetch(
                    target["url"] + "/flight", self.timeout_s
                )
                events = payload["events"]
            except Exception:  # noqa: BLE001 - any scrape failure = missing
                missing.append(name)
                continue
            sources.append(name)
            for e in events:
                if isinstance(e, dict):
                    raw.append({**e, "source": name})
        # the frontend's own ring rides along: router-side events
        # (dead/rejoin/drain, slo_burn, replica_outlier) live here
        for e in flight_recorder.events():
            raw.append({**e, "source": "frontend"})
        merged = merge_incident_events(
            raw, self.offsets, now_wall=self._wall(), window_s=window_s,
        )
        trace = incident_trace(merged)
        return {
            "window_s": window_s,
            "replicas": sources,
            "partial": bool(missing),
            "missing": sorted(missing),
            "offsets": self.offsets.snapshot(),
            "events": merged,
            "phases": incident_phases(merged),
            "trace": trace,
        }


# ------------------------------------------------------- incident rendering
def merge_incident_events(
    events: List[dict],
    offsets: ClockOffsets,
    now_wall: float,
    window_s: float = 0.0,
) -> List[dict]:
    """Offset-correct, window, dedup, and causally order raw flight
    events from N rings. Each event's lane is its ``replica`` field (the
    identity stamp every fleet event carries) falling back to the ring
    it was scraped from; the corrected stamp ``ts_corrected`` maps the
    producer's wall clock onto the caller's via the offset estimates, so
    two replicas with ±500 ms of wall skew still interleave in true
    causal order (to ~rtt/2 accuracy). In-process fleets share one ring
    between replicas, so identical events scraped N times collapse."""
    seen = set()
    out = []
    for e in events:
        lane = str(e.get("replica") or e.get("source") or "")
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        source = str(e.get("source") or "")
        corrected = offsets.correct(source, float(ts))
        if window_s and corrected < now_wall - window_s:  # graphlint: wallclock -- lookback cut over offset-corrected stamps
            continue
        key = (e.get("seq"), round(float(ts), 6), e.get("category"), lane)
        if key in seen:
            continue
        seen.add(key)
        out.append({
            **{k: v for k, v in e.items() if k != "source"},
            "lane": lane,
            "ts_corrected": corrected,
        })
    out.sort(key=lambda e: (e["ts_corrected"], e.get("seq", 0)))
    return out


def incident_phases(merged: List[dict]) -> List[dict]:
    """The failover narrative: the first corrected-time occurrence of
    each phase boundary (kill -> mark_dead -> re-pin -> warm-up). When
    the stream contains a kill, the narrative anchors there — joins and
    warm-ups from the ORIGINAL fleet bring-up (before the kill) are
    bring-up, not failover, and must not claim a phase slot."""
    anchor = float("-inf")
    kill_match = _PHASE_EVENTS[0][1]
    for e in merged:
        if kill_match(e):
            anchor = e["ts_corrected"]
            break
    phases = []
    for phase, match in _PHASE_EVENTS:
        for e in merged:
            if e["ts_corrected"] >= anchor and match(e):
                phases.append({
                    "phase": phase,
                    "ts_corrected": e["ts_corrected"],
                    "lane": e["lane"],
                    "category": e.get("category"),
                    "detail": e.get("action") or e.get("kind"),
                })
                break
    return phases


def incident_trace(merged: List[dict]) -> dict:
    """One Chrome-trace document over the merged incident: a lane (tid)
    per replica, one instant event per flight record at its corrected
    time — loads in chrome://tracing / ui.perfetto.dev next to the PR 13
    OLAP timelines (same catapult vocabulary, validate_chrome_trace
    clean)."""
    from janusgraph_tpu.observability.timeline import PID, _meta

    lanes = sorted({e["lane"] for e in merged})
    tid_of = {lane: i + 1 for i, lane in enumerate(lanes)}
    events = [_meta("process_name", 0, "fleet incident")]
    for lane in lanes:
        events.append(
            _meta("thread_name", tid_of[lane], f"replica {lane}" if lane else "untagged")
        )
    t0 = merged[0]["ts_corrected"] if merged else 0.0
    for e in merged:
        name = str(e.get("category", "event"))
        detail = e.get("action") or e.get("kind")
        if detail:
            name = f"{name}:{detail}"
        args = {
            k: v for k, v in e.items()
            if k not in ("lane", "ts_corrected") and isinstance(
                v, (str, int, float, bool, type(None))
            )
        }
        events.append({
            "ph": "i", "pid": PID, "tid": tid_of[e["lane"]],
            "name": name, "s": "t",
            "ts": round((e["ts_corrected"] - t0) * 1e6, 3),  # graphlint: wallclock -- trace-axis placement of corrected stamps relative to incident start
            "args": args,
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "kind": "fleet-incident",
            "lanes": lanes,
            "events": len(merged),
        },
    }
