"""Structured spans: a context-var tracer with parent/child nesting.

The reference has Gremlin ``.profile()`` for one traversal at a time;
spans generalize that to every subsystem: the OLTP tx lifecycle
(commit/rollback, lock acquisition, index queries), the storage backend
(instrumented ``get_slice``/``mutate``, scan jobs) and the OLAP
``GraphComputer.submit()`` path down to per-superstep children.

Design:

- ``contextvars`` carry the current span, so nesting follows Python's
  call/async structure per thread with zero plumbing; a thread (or
  context) always builds its own tree.
- finished ROOT spans land in a bounded ring buffer (``recent()``); the
  process never accumulates unbounded trees.
- every finished span — root or child — whose duration crosses the
  configured threshold is ALSO appended to the slow-op ring buffer
  (``slow_ops()``), the always-on flight recorder for outliers
  (threshold via ``metrics.slow-op-threshold-ms`` in core/config.py).
- pre-timed children (``record_span``) let host-resident measurements —
  e.g. per-superstep records reduced on device and fetched once — appear
  in the tree without ever recording from traced code (graphlint JG106).
- every span carries a 64-bit ``trace_id``/``span_id``; a
  :class:`TraceContext` serializes (trace_id, parent span_id, sampled)
  compactly for process boundaries — the remote KCVS/index protocols
  prepend it to op frames, the query server reads it from an
  ``X-Trace-Context`` header — so one user query stitches into ONE trace
  across client, server, and storage nodes (inspect via ``GET /telemetry``
  or ``janusgraph_tpu trace <trace_id>``).
"""

from __future__ import annotations

import contextvars
import random
import struct
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "janusgraph_tpu_current_span", default=None
)


def _new_id() -> int:
    """Non-zero 64-bit id. `random` (not urandom syscalls): ids only need
    collision resistance within a ring buffer, and spans sit on the tx
    hot path."""
    v = random.getrandbits(64)
    return v or 1


class TraceContext:
    """The serializable slice of a span that crosses process boundaries:
    (trace_id, parent span_id, sampled flag).

    Two codecs, both versioned:

    - ``to_bytes``/``from_bytes`` — compact binary for the length-prefixed
      storage/index protocols: ``[ver:1][trace_id:8][span_id:8][flags:1]``.
    - ``to_header``/``from_header`` — W3C-traceparent-shaped text for the
      HTTP/WS query protocol: ``01-<trace:16hex>-<span:16hex>-<flags:2hex>``.

    Decoders return ``None`` on anything malformed: a bad trace header
    must never fail the request it rides on.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    _VERSION = 1

    def __init__(self, trace_id: int, span_id: int, sampled: bool = True):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)
        self.sampled = bool(sampled)

    def to_bytes(self) -> bytes:
        return struct.pack(
            ">BQQB", self._VERSION, self.trace_id, self.span_id,
            1 if self.sampled else 0,
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> Optional["TraceContext"]:
        if len(raw) != 18:
            return None
        ver, trace_id, span_id, flags = struct.unpack(">BQQB", raw)
        if ver != cls._VERSION or trace_id == 0:
            return None
        return cls(trace_id, span_id, sampled=bool(flags & 1))

    def to_header(self) -> str:
        return (
            f"{self._VERSION:02d}-{self.trace_id:016x}-{self.span_id:016x}"
            f"-{1 if self.sampled else 0:02x}"
        )

    @classmethod
    def from_header(cls, text: str) -> Optional["TraceContext"]:
        if not text:
            return None
        parts = text.strip().split("-")
        if len(parts) != 4:
            return None
        try:
            ver = int(parts[0], 10)
            trace_id = int(parts[1], 16)
            span_id = int(parts[2], 16)
            flags = int(parts[3], 16)
        except ValueError:
            return None
        if ver != cls._VERSION or trace_id == 0:
            return None
        return cls(trace_id, span_id, sampled=bool(flags & 1))

    def __repr__(self) -> str:
        return f"TraceContext({self.to_header()})"


def _plain(value):
    """Attribute values must be JSON-friendly host scalars — coercing a
    traced/device value here would be a hidden sync, so only coerce known
    host types and stringify the rest."""
    if isinstance(value, (str, bool, int, float)) or value is None:
        return value
    try:
        import numpy as np

        if isinstance(value, np.integer):
            return int(value)
        if isinstance(value, np.floating):
            return float(value)
    except ImportError:  # numpy is always present here, but be safe
        pass
    return str(value)


class Span:
    """One timed node: name, attributes, children (cf. the profiler's
    QueryProfiler group, but subsystem-agnostic and context-propagated).
    Carries trace identity: ``trace_id`` is shared by every span of one
    logical operation (across processes when propagated),
    ``parent_span_id`` links a local root under its remote parent."""

    __slots__ = (
        "name", "attrs", "children", "start_ns", "end_ns", "wall_t",
        "trace_id", "span_id", "parent_span_id", "sampled",
    )

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs: Dict[str, object] = (
            {k: _plain(v) for k, v in attrs.items()} if attrs else {}
        )
        self.children: List["Span"] = []
        self.start_ns = 0
        self.end_ns = 0
        self.wall_t = 0.0  # epoch seconds at start (for the slow-op log)
        self.span_id = _new_id()
        self.trace_id = 0  # assigned at attach: inherited or fresh
        self.parent_span_id = 0  # non-zero only for remote-parented roots
        self.sampled = True

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def context(self) -> TraceContext:
        """This span's identity as a propagatable context."""
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    def annotate(self, **attrs) -> "Span":
        for k, v in attrs.items():
            self.attrs[k] = _plain(v)
        return self

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "duration_ms": round(self.duration_ms, 4),
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }
        if self.parent_span_id:
            d["parent_span_id"] = f"{self.parent_span_id:016x}"
        return d

    def find(self, name: str) -> List["Span"]:
        """All descendants (and self) with this name, depth-first."""
        out = [self] if self.name == name else []
        for c in self.children:
            out.extend(c.find(name))
        return out


class Tracer:
    """Owns the current-span context plus the two ring buffers."""

    def __init__(
        self,
        max_roots: int = 256,
        slow_threshold_ms: float = 100.0,
        slow_buffer: int = 128,
    ):
        self.slow_threshold_ms = slow_threshold_ms
        self._roots: deque = deque(maxlen=max_roots)
        self._slow: deque = deque(maxlen=slow_buffer)
        self._lock = threading.Lock()
        #: optional sink fed every slow-op event (the flight recorder
        #: registers here; observability/__init__.py wires it)
        self.on_slow = None

    def configure(
        self,
        max_roots: Optional[int] = None,
        slow_threshold_ms: Optional[float] = None,
        slow_buffer: Optional[int] = None,
    ) -> None:
        with self._lock:
            if slow_threshold_ms is not None:
                self.slow_threshold_ms = slow_threshold_ms
            if max_roots is not None and max_roots != self._roots.maxlen:
                self._roots = deque(self._roots, maxlen=max_roots)
            if slow_buffer is not None and slow_buffer != self._slow.maxlen:
                self._slow = deque(self._slow, maxlen=slow_buffer)

    # -------------------------------------------------------------- recording
    @contextmanager
    def span(self, name: str, **attrs):
        parent = _CURRENT.get()
        s = Span(name, attrs)
        if parent is not None:
            parent.children.append(s)
            s.trace_id = parent.trace_id
            s.sampled = parent.sampled
        else:
            s.trace_id = _new_id()
        token = _CURRENT.set(s)
        s.wall_t = time.time()
        s.start_ns = time.perf_counter_ns()
        try:
            yield s
        finally:
            s.end_ns = time.perf_counter_ns()
            _CURRENT.reset(token)
            self._finished(s, root=parent is None)

    @contextmanager
    def child_span(self, ctx: Optional[TraceContext], name: str, **attrs):
        """A span under a REMOTE parent: joins ctx's trace as a local root
        (it lands in this process's root ring, linked by
        ``parent_span_id``). With ``ctx=None`` this is a plain ``span`` —
        receive sites never need to branch on whether a peer propagated."""
        if ctx is None:
            with self.span(name, **attrs) as s:
                yield s
            return
        parent = _CURRENT.get()
        s = Span(name, attrs)
        s.trace_id = ctx.trace_id
        s.parent_span_id = ctx.span_id
        s.sampled = ctx.sampled
        if parent is not None:
            # a remote context wins over the ambient span: the handler
            # thread's tree keeps its shape, the ids join the caller's trace
            parent.children.append(s)
        token = _CURRENT.set(s)
        s.wall_t = time.time()
        s.start_ns = time.perf_counter_ns()
        try:
            yield s
        finally:
            s.end_ns = time.perf_counter_ns()
            _CURRENT.reset(token)
            self._finished(s, root=parent is None)

    def record_span(self, name: str, duration_ms: float, **attrs) -> Span:
        """Attach a pre-timed span under the current span (or as a root).
        For measurements taken elsewhere — per-superstep records pulled
        from host-resident reduced metrics, never from traced code."""
        parent = _CURRENT.get()
        s = Span(name, attrs)
        now = time.perf_counter_ns()
        # graphlint: wallclock -- reconstructs the wall START STAMP of a pre-timed span (duration_ms was measured elsewhere, on a monotonic clock)
        s.wall_t = time.time() - duration_ms / 1e3
        s.start_ns = now - int(duration_ms * 1e6)
        s.end_ns = now
        if parent is not None:
            parent.children.append(s)
            s.trace_id = parent.trace_id
            s.sampled = parent.sampled
        else:
            s.trace_id = _new_id()
        self._finished(s, root=parent is None)
        return s

    def _finished(self, s: Span, root: bool) -> None:
        thr = self.slow_threshold_ms
        if thr > 0 and s.duration_ms >= thr:
            event = {
                "name": s.name,
                "ms": round(s.duration_ms, 3),
                "time": s.wall_t,
                "trace_id": f"{s.trace_id:016x}",
                "span_id": f"{s.span_id:016x}",
                "attrs": dict(s.attrs),
            }
            with self._lock:
                self._slow.append(event)
            sink = self.on_slow
            if sink is not None:
                try:
                    sink(dict(event))
                except Exception:  # noqa: BLE001 - telemetry must not break work
                    pass
        if root and s.sampled:
            with self._lock:
                self._roots.append(s)

    # -------------------------------------------------------------- querying
    def current(self) -> Optional[Span]:
        return _CURRENT.get()

    def current_context(self) -> Optional[TraceContext]:
        """The ambient span's propagatable identity (None outside spans)."""
        cur = _CURRENT.get()
        return cur.context() if cur is not None else None

    def recent(self, name: Optional[str] = None) -> List[Span]:
        """Completed root spans, oldest first (optionally name-filtered)."""
        with self._lock:
            roots = list(self._roots)
        if name is not None:
            roots = [r for r in roots if r.name == name]
        return roots

    def find_trace(self, trace_id) -> List[Span]:
        """Every retained root span belonging to one trace, oldest first.
        Accepts an int or the 16-hex-char form the JSON surfaces use."""
        if isinstance(trace_id, str):
            try:
                trace_id = int(trace_id, 16)
            except ValueError:
                return []
        with self._lock:
            roots = list(self._roots)
        return [r for r in roots if r.trace_id == trace_id]

    def slow_ops(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._slow]

    def reset(self) -> None:
        with self._lock:
            self._roots.clear()
            self._slow.clear()


def capture_scope(fn):
    """Bind ``fn`` to the caller's ambient scope for execution on another
    thread.

    ``contextvars`` do not cross thread boundaries: a pool worker starts
    from an empty context, so the submitting request's current span,
    deadline, and profiler ledger silently vanish (graphlint JG402). This
    is the explicit handoff: it snapshots every contextvar at call time
    and returns a wrapper that re-enters the snapshot around each
    invocation::

        with span("store.scan"):
            pool.map(capture_scope(work), splits)   # workers keep the span

    Each invocation sets/resets the vars on its own thread rather than
    sharing one ``Context.run`` — a single ``Context`` object refuses
    concurrent entry, and pool workers run concurrently by design.
    """
    snapshot = list(contextvars.copy_context().items())

    def _reentered(*args, **kwargs):
        tokens = [(var, var.set(value)) for var, value in snapshot]
        try:
            return fn(*args, **kwargs)
        finally:
            for var, token in reversed(tokens):
                var.reset(token)

    return _reentered


#: process-wide tracer; `janusgraph_tpu.observability.span` is its
#: `span` method
tracer = Tracer()
