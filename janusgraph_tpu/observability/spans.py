"""Structured spans: a context-var tracer with parent/child nesting.

The reference has Gremlin ``.profile()`` for one traversal at a time;
spans generalize that to every subsystem: the OLTP tx lifecycle
(commit/rollback, lock acquisition, index queries), the storage backend
(instrumented ``get_slice``/``mutate``, scan jobs) and the OLAP
``GraphComputer.submit()`` path down to per-superstep children.

Design:

- ``contextvars`` carry the current span, so nesting follows Python's
  call/async structure per thread with zero plumbing; a thread (or
  context) always builds its own tree.
- finished ROOT spans land in a bounded ring buffer (``recent()``); the
  process never accumulates unbounded trees.
- every finished span — root or child — whose duration crosses the
  configured threshold is ALSO appended to the slow-op ring buffer
  (``slow_ops()``), the always-on flight recorder for outliers
  (threshold via ``metrics.slow-op-threshold-ms`` in core/config.py).
- pre-timed children (``record_span``) let host-resident measurements —
  e.g. per-superstep records reduced on device and fetched once — appear
  in the tree without ever recording from traced code (graphlint JG106).
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "janusgraph_tpu_current_span", default=None
)


def _plain(value):
    """Attribute values must be JSON-friendly host scalars — coercing a
    traced/device value here would be a hidden sync, so only coerce known
    host types and stringify the rest."""
    if isinstance(value, (str, bool, int, float)) or value is None:
        return value
    try:
        import numpy as np

        if isinstance(value, np.integer):
            return int(value)
        if isinstance(value, np.floating):
            return float(value)
    except ImportError:  # numpy is always present here, but be safe
        pass
    return str(value)


class Span:
    """One timed node: name, attributes, children (cf. the profiler's
    QueryProfiler group, but subsystem-agnostic and context-propagated)."""

    __slots__ = ("name", "attrs", "children", "start_ns", "end_ns", "wall_t")

    def __init__(self, name: str, attrs: Optional[dict] = None):
        self.name = name
        self.attrs: Dict[str, object] = (
            {k: _plain(v) for k, v in attrs.items()} if attrs else {}
        )
        self.children: List["Span"] = []
        self.start_ns = 0
        self.end_ns = 0
        self.wall_t = 0.0  # epoch seconds at start (for the slow-op log)

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def annotate(self, **attrs) -> "Span":
        for k, v in attrs.items():
            self.attrs[k] = _plain(v)
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration_ms": round(self.duration_ms, 4),
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }

    def find(self, name: str) -> List["Span"]:
        """All descendants (and self) with this name, depth-first."""
        out = [self] if self.name == name else []
        for c in self.children:
            out.extend(c.find(name))
        return out


class Tracer:
    """Owns the current-span context plus the two ring buffers."""

    def __init__(
        self,
        max_roots: int = 256,
        slow_threshold_ms: float = 100.0,
        slow_buffer: int = 128,
    ):
        self.slow_threshold_ms = slow_threshold_ms
        self._roots: deque = deque(maxlen=max_roots)
        self._slow: deque = deque(maxlen=slow_buffer)
        self._lock = threading.Lock()

    def configure(
        self,
        max_roots: Optional[int] = None,
        slow_threshold_ms: Optional[float] = None,
        slow_buffer: Optional[int] = None,
    ) -> None:
        with self._lock:
            if slow_threshold_ms is not None:
                self.slow_threshold_ms = slow_threshold_ms
            if max_roots is not None and max_roots != self._roots.maxlen:
                self._roots = deque(self._roots, maxlen=max_roots)
            if slow_buffer is not None and slow_buffer != self._slow.maxlen:
                self._slow = deque(self._slow, maxlen=slow_buffer)

    # -------------------------------------------------------------- recording
    @contextmanager
    def span(self, name: str, **attrs):
        parent = _CURRENT.get()
        s = Span(name, attrs)
        if parent is not None:
            parent.children.append(s)
        token = _CURRENT.set(s)
        s.wall_t = time.time()
        s.start_ns = time.perf_counter_ns()
        try:
            yield s
        finally:
            s.end_ns = time.perf_counter_ns()
            _CURRENT.reset(token)
            self._finished(s, root=parent is None)

    def record_span(self, name: str, duration_ms: float, **attrs) -> Span:
        """Attach a pre-timed span under the current span (or as a root).
        For measurements taken elsewhere — per-superstep records pulled
        from host-resident reduced metrics, never from traced code."""
        parent = _CURRENT.get()
        s = Span(name, attrs)
        now = time.perf_counter_ns()
        s.wall_t = time.time() - duration_ms / 1e3
        s.start_ns = now - int(duration_ms * 1e6)
        s.end_ns = now
        if parent is not None:
            parent.children.append(s)
        self._finished(s, root=parent is None)
        return s

    def _finished(self, s: Span, root: bool) -> None:
        thr = self.slow_threshold_ms
        if thr > 0 and s.duration_ms >= thr:
            with self._lock:
                self._slow.append({
                    "name": s.name,
                    "ms": round(s.duration_ms, 3),
                    "time": s.wall_t,
                    "attrs": dict(s.attrs),
                })
        if root:
            with self._lock:
                self._roots.append(s)

    # -------------------------------------------------------------- querying
    def current(self) -> Optional[Span]:
        return _CURRENT.get()

    def recent(self, name: Optional[str] = None) -> List[Span]:
        """Completed root spans, oldest first (optionally name-filtered)."""
        with self._lock:
            roots = list(self._roots)
        if name is not None:
            roots = [r for r in roots if r.name == name]
        return roots

    def slow_ops(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._slow]

    def reset(self) -> None:
        with self._lock:
            self._roots.clear()
            self._slow.clear()


#: process-wide tracer; `janusgraph_tpu.observability.span` is its
#: `span` method
tracer = Tracer()
