"""Bench regression sentinel: compare bench artifacts, verdict deltas.

The bench trajectory (BENCH_r01.., MULTICHIP_r01.., SATURATE_r01..) has
been eyeballed JSON so far. This module makes regressions a computed,
CI-gateable verdict:

- an **artifact** is any of the shapes the bench has ever written: one
  stage dict (SATURATE_r01.json), a supervisor wrapper with a ``tail``
  of per-stage JSON lines (BENCH_r05.json), a ``.jsonl`` of stage lines
  (bench_artifacts/*.jsonl), or a list of stage dicts.
  :func:`load_stages` normalizes all of them to a stage-dict list.

- stages are matched by **cell**: ``(stage, scale, platform/device_kind,
  host-fallback flag)`` — a CPU-fallback number must never gate a TPU
  number and vice versa.

- each stage has **headline metrics** with an explicit better-direction
  (lower for walls/latencies/pad, higher for goodput/speedups); unknown
  stages fall back to suffix conventions (``*_ms``/``*_wall_s`` lower,
  ``*_per_s``/``*speedup*`` higher).

- :func:`compare` computes per-metric deltas and classifies each as
  ``improve`` / ``regress`` / ``noise`` against a relative threshold
  (default 10% — chosen under the observed inter-round jitter of the
  CPU-host rounds, and below the 20% synthetic-regression acceptance
  bar). The stage verdict is ``regress`` if ANY headline metric
  regressed, else ``improve`` if any improved, else ``noise``.

- :func:`best_prior` picks, among prior artifacts matching a cell, the
  stage with the best primary (first headline) metric — the bench
  compares against the best it has ever demonstrated, not just the last
  round, so a slow round followed by another slow round still flags.

``bench.py`` attaches a ``regression`` block to every emitted stage by
default (no-op note when no prior artifact matches the cell);
``janusgraph_tpu benchdiff <old> <new> [--fail-on-regress]`` is the CI
entry point and ``bin/benchdiff.sh`` wraps it.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

LOWER = "lower"
HIGHER = "higher"

#: per-stage headline metrics, primary first: (metric key, better-dir).
#: Only keys PRESENT in both stages are compared.
HEADLINES: Dict[str, List[Tuple[str, str]]] = {
    "pagerank": [
        ("pagerank_superstep_ms", LOWER),
        ("pagerank_wall_s", LOWER),
        ("ell_pad_ratio", LOWER),
        ("edges_per_sec", HIGHER),
    ],
    "bfs": [("bfs_4hop_wall_s", LOWER)],
    "bfs_dense": [
        ("bfs_dense_4hop_wall_s", LOWER),
        ("bfs_frontier_speedup", HIGHER),
    ],
    "oltp": [
        ("oltp_write_per_s", HIGHER),
        ("oltp_read_per_s", HIGHER),
        ("oltp_3hop_ms", LOWER),
    ],
    "oltp_pipeline": [("pipelined_speedup", HIGHER)],
    "oltp_spillover": [
        ("spill_3hop_speedup", HIGHER),
        ("spill_4hop_speedup", HIGHER),
    ],
    "streaming_freshness": [
        ("refresh_speedup", HIGHER),
        ("refresh_median_ms", LOWER),
        ("staleness_window_ms", LOWER),
        ("writes_per_s", HIGHER),
    ],
    "dense_gcn": [
        ("superstep_ms", LOWER),
        ("mxu_utilization", HIGHER),
    ],
    "workload": [("wall_s", LOWER)],
    "dataset": [("wall_s", LOWER)],
    "saturate": [
        ("peak_goodput_per_s", HIGHER),
        ("goodput_2x_over_peak", HIGHER),
    ],
    "fleet_chaos": [
        ("failover_p99_ms", LOWER),
        ("goodput_during_kill_over_prekill", HIGHER),
        ("goodput_recovered_over_prekill", HIGHER),
        ("pre_kill_goodput_per_s", HIGHER),
    ],
    # PR 18: leader-kill failover certified from the durable CDC log —
    # promotion latency first (the availability gap), then the staleness
    # ceiling followers actually served at, then the read share they
    # absorbed (the scale-out payoff)
    "fleet_cdc_failover": [
        ("promote_ms", LOWER),
        ("staleness_p99_ms", LOWER),
        ("follower_read_share", HIGHER),
    ],
    # PR 19: seeded stall forensics — detection latency is the headline
    # (stall onset -> lock_convoy flight event); everything else in the
    # stage is boolean acceptance, not a trend
    "fleet_stall_forensics": [("detect_ms", LOWER)],
    # PR 20: streaming telemetry — push-mode event freshness first (the
    # latency collapse push exists for), then the bus's own CPU bill;
    # loss/duplication in the stage are boolean acceptance, not trends
    "fleet_push_poll": [
        ("push_event_p99_ms", LOWER),
        ("bus_cpu_overhead_pct", LOWER),
        ("push_vs_poll_speedup", HIGHER),
    ],
    "multichip_ab": [("superstep_ms", LOWER)],
    "chaos": [("recovery_open_ms", LOWER)],
    "smoke": [],
}

#: suffix conventions for stages without an explicit headline list
_SUFFIX_DIRS = (
    ("_ms", LOWER), ("_wall_s", LOWER), ("_pad_ratio", LOWER),
    ("_per_s", HIGHER), ("_per_sec", HIGHER), ("speedup", HIGHER),
    ("goodput", HIGHER), ("utilization", HIGHER),
)

#: default relative noise threshold (see module doc)
NOISE_THRESHOLD = 0.10


def headline_metrics(stage: dict) -> List[Tuple[str, str]]:
    """(key, better-dir) pairs for one stage dict, primary first."""
    explicit = HEADLINES.get(str(stage.get("stage", "")))
    if explicit is not None:
        return [m for m in explicit if _numeric(stage.get(m[0]))]
    out = []
    for key in sorted(stage):
        if not _numeric(stage.get(key)):
            continue
        for suffix, direction in _SUFFIX_DIRS:
            if key.endswith(suffix) or suffix in key:
                out.append((key, direction))
                break
    return out


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# ------------------------------------------------------------------ loading
def load_stages(path: str) -> List[dict]:
    """Every stage dict found in one artifact file (see module doc for
    the accepted shapes). Unparseable lines are skipped, not fatal."""
    stages: List[dict] = []
    with open(path) as f:
        raw = f.read()
    if path.endswith(".jsonl"):
        docs = _parse_lines(raw)
    else:
        try:
            docs = [json.loads(raw)]
        except json.JSONDecodeError:
            docs = _parse_lines(raw)
    for doc in docs:
        stages.extend(_stages_of(doc))
    return stages


def _parse_lines(raw: str) -> List[dict]:
    out = []
    for ln in raw.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    return out


def _stages_of(doc) -> List[dict]:
    if isinstance(doc, list):
        out = []
        for d in doc:
            out.extend(_stages_of(d))
        return out
    if not isinstance(doc, dict):
        return []
    if "stage" in doc:
        return [doc]
    out = []
    for key in ("stages", "parsed"):
        if key in doc:
            out.extend(_stages_of(doc[key]))
    # supervisor wrappers carry stage JSON objects embedded in a `tail`
    # text blob: recover whole JSON objects from it
    tail = doc.get("tail")
    if isinstance(tail, str):
        out.extend(s for s in _scan_json_objects(tail) if "stage" in s)
    return out


def _scan_json_objects(text: str) -> List[dict]:
    """Top-level JSON objects embedded anywhere in a text blob."""
    decoder = json.JSONDecoder()
    out = []
    i = 0
    n = len(text)
    while i < n:
        j = text.find("{", i)
        if j < 0:
            break
        try:
            obj, end = decoder.raw_decode(text, j)
        except json.JSONDecodeError:
            i = j + 1
            continue
        if isinstance(obj, dict):
            out.append(obj)
        i = end
    return out


# --------------------------------------------------------------------- cells
#: cell component names, aligned with cell_key()'s tuple order — the
#: no_baseline note names which of these a near-miss differs on
CELL_FIELDS = ("stage", "scale", "platform", "host_fallback", "cpu_count")


def cell_key(stage: dict) -> Tuple:
    """The comparability cell: (stage, scale, platform, host-fallback,
    host cpu_count). cpu_count joined after the SATURATE r01->r03
    424->360 ops/s mystery turned out to be a 1-core runner: throughput
    cells from hosts with different core counts are not comparable, so
    they must not verdict against each other. Artifacts predating the
    field carry cpu_count=None and keep matching each other."""
    return (
        str(stage.get("stage", "")),
        stage.get("scale"),
        str(stage.get("platform", stage.get("device_kind", "")) or ""),
        bool(stage.get("host_fallback", False)),
        stage.get("cpu_count"),
    )


def nearest_cell_mismatch(
    stages: List[dict], cell: Tuple
) -> Optional[str]:
    """When no prior artifact matches a cell exactly, name the key
    component(s) the CLOSEST near-miss differs on (same stage name,
    fewest differing components) — so a no_baseline verdict says
    "prior cells exist but differ on cpu_count" instead of leaving the
    operator to diff tuples by hand."""
    best_diff: Optional[List[str]] = None
    for s in stages:
        k = cell_key(s)
        if k == cell or k[0] != cell[0]:
            continue
        diff = [
            CELL_FIELDS[i]
            for i in range(1, len(CELL_FIELDS))
            if k[i] != cell[i]
        ]
        if best_diff is None or len(diff) < len(best_diff):
            best_diff = diff
    if not best_diff:
        return None
    return "nearest prior cell differs on: " + ", ".join(best_diff)


def best_prior(
    stages: List[dict], cell: Tuple
) -> Optional[dict]:
    """Best prior stage for a cell: the one with the best PRIMARY
    headline metric (ties/absence resolve to the last seen)."""
    candidates = [s for s in stages if cell_key(s) == cell]
    if not candidates:
        return None
    best = None
    best_val = None
    best_dir = None
    for s in candidates:
        metrics = headline_metrics(s)
        if not metrics:
            best = s  # keep SOMETHING comparable (e.g. smoke)
            continue
        key, direction = metrics[0]
        v = s[key]
        if best_val is None or (
            v < best_val if direction == LOWER else v > best_val
        ):
            best, best_val, best_dir = s, v, direction
    del best_dir
    return best


# ------------------------------------------------------------------ compare
def compare(
    old: dict, new: dict, threshold: float = NOISE_THRESHOLD
) -> dict:
    """Per-metric deltas + verdict for two stage dicts of one cell."""
    metrics = []
    verdicts = set()
    for key, direction in headline_metrics(new):
        if not _numeric(old.get(key)):
            continue
        ov, nv = float(old[key]), float(new[key])
        delta = nv - ov
        rel = delta / abs(ov) if ov else (0.0 if nv == 0 else float("inf"))
        worse = rel > 0 if direction == LOWER else rel < 0
        if abs(rel) <= threshold:
            verdict = "noise"
        elif worse:
            verdict = "regress"
        else:
            verdict = "improve"
        verdicts.add(verdict)
        metrics.append({
            "metric": key,
            "better": direction,
            "old": ov,
            "new": nv,
            "delta": round(delta, 6),
            "delta_pct": (
                round(rel * 100.0, 2) if rel != float("inf") else None
            ),
            "verdict": verdict,
        })
    if "regress" in verdicts:
        overall = "regress"
    elif "improve" in verdicts:
        overall = "improve"
    elif metrics:
        overall = "noise"
    else:
        overall = "incomparable"
    out = {
        "verdict": overall,
        "threshold_pct": round(threshold * 100.0, 2),
        "cell": list(cell_key(new)),
        "metrics": metrics,
    }
    if overall == "regress":
        deltas = _frame_deltas(old, new)
        if deltas:
            out["frame_deltas"] = deltas
    return out


def _frame_deltas(old: dict, new: dict, top: int = 3) -> List[dict]:
    """Top frame-level flame deltas between two stages that both embed
    profile data (``flame``/``stacks`` blocks from the continuous
    sampling profiler) — WHERE the regressed time went, not just that it
    went. Empty when either side carries no profile."""
    try:
        from janusgraph_tpu.observability.continuous import flamediff

        return flamediff(old, new, top=top)
    except Exception:  # noqa: BLE001 - sentinel never fails a bench
        return []


def diff_artifacts(
    old_path: str, new_path: str, threshold: float = NOISE_THRESHOLD
) -> dict:
    """Compare every cell present in BOTH artifacts. The `janusgraph_tpu
    benchdiff` payload: per-cell comparison blocks + roll-up counts."""
    old_stages = load_stages(old_path)
    new_stages = load_stages(new_path)
    comparisons = []
    seen = set()
    for s in new_stages:
        cell = cell_key(s)
        if cell in seen:
            continue
        seen.add(cell)
        prior = best_prior(old_stages, cell)
        if prior is None:
            continue
        comparisons.append(compare(prior, s, threshold))
    counts: Dict[str, int] = {}
    for c in comparisons:
        counts[c["verdict"]] = counts.get(c["verdict"], 0) + 1
    return {
        "old": os.path.basename(old_path),
        "new": os.path.basename(new_path),
        "cells_compared": len(comparisons),
        "counts": counts,
        "regressed": counts.get("regress", 0) > 0,
        "comparisons": comparisons,
    }


# ----------------------------------------------------- bench-side attachment
class BaselineIndex:
    """Prior-artifact stages indexed once per process (bench.py attaches
    a regression block to every emitted stage through this)."""

    def __init__(self, search_dirs: List[str]):
        self.search_dirs = search_dirs
        self._stages: Optional[List[dict]] = None

    def stages(self) -> List[dict]:
        if self._stages is None:
            stages: List[dict] = []
            for d in self.search_dirs:
                if not os.path.isdir(d):
                    continue
                for fn in sorted(os.listdir(d)):
                    if not (fn.endswith(".json") or fn.endswith(".jsonl")):
                        continue
                    try:
                        stages.extend(load_stages(os.path.join(d, fn)))
                    except OSError:
                        continue
            self._stages = stages
        return self._stages

    def attach_regression(
        self, stage: dict, threshold: float = NOISE_THRESHOLD
    ) -> dict:
        """Mutates ``stage``: adds the ``regression`` verdict block (or a
        no-op note when no prior artifact matches its cell). Never
        raises — the sentinel must not fail a bench run."""
        try:
            if not headline_metrics(stage):
                return stage
            cell = cell_key(stage)
            prior = best_prior(self.stages(), cell)
            if prior is None or prior is stage:
                note = "no prior artifact matches this cell"
                mismatch = nearest_cell_mismatch(self.stages(), cell)
                if mismatch:
                    note = f"{note} ({mismatch})"
                stage["regression"] = {
                    "verdict": "no_baseline",
                    "note": note,
                    "cell": list(cell),
                }
                return stage
            stage["regression"] = compare(prior, stage, threshold)
        except Exception as e:  # noqa: BLE001 - sentinel never fails a bench
            stage["regression"] = {
                "verdict": "error", "note": f"{type(e).__name__}: {e}"[:200],
            }
        return stage
