"""Unified telemetry: histogram metrics, structured spans, exposition.

The subsystem the reference spreads across its Dropwizard stack
(reference: util/stats/MetricManager.java:36 registry singleton,
MetricInstrumentedStore.java per-store timers, per-tx metric groups
StandardJanusGraphTx.java:258-262, reporters
GraphDatabaseConfiguration.java:1012-1094) plus what it does NOT have —
a span tracer and OLAP superstep telemetry for the TPU path (compile vs
execute split, retraces, transfer bytes, frontier occupancy, ELL pad
waste), the quantities that actually dominate graph-engine performance
(PAPERS.md: arxiv 2011.08451 propagation blocking, 2108.11521 on-chip
communication for graph analytics).

Layout:

- ``metrics_core``: :class:`Counter`, :class:`Timer`, :class:`Histogram`,
  :class:`Gauge`, and :class:`TelemetryRegistry` — the registry that
  ``janusgraph_tpu.util.metrics`` re-exports as its ``metrics`` singleton
  (absorbed from the old ``MetricManager``).
- ``spans``: context-var tracer with parent/child nesting and the
  always-on slow-op ring buffer.
- ``exposition``: Prometheus-text and JSON snapshot renderers served at
  ``GET /metrics`` / ``GET /telemetry`` and by
  ``python -m janusgraph_tpu telemetry``.

Recording is HOST-ONLY by contract: no metric or span call may run inside
jit-traced code (it would record once per compile, not per execution, and
coercing tracer attribute values forces a device sync). graphlint rule
JG106 enforces this mechanically.
"""

from janusgraph_tpu.observability.continuous import (
    BundleWriter,
    InstrumentedLock,
    SamplingProfiler,
    StallWatchdog,
    bundle_writer,
    flame_from_artifact,
    flamediff,
    sampling_profiler,
    watchdog,
)
from janusgraph_tpu.observability.exposition import (
    json_snapshot,
    prometheus_text,
)
from janusgraph_tpu.observability.federation import (
    ClockOffsets,
    FleetBundleStore,
    FleetFederation,
    FleetHistory,
    fleet_default_specs,
    merge_incident_events,
    merge_series,
    merge_windows,
)
from janusgraph_tpu.observability.flight import FlightRecorder
from janusgraph_tpu.observability.flight import recorder as flight_recorder
from janusgraph_tpu.observability.identity import (
    replica_name,
    set_replica,
)
from janusgraph_tpu.observability.logging import (
    StructuredLogger,
    get_logger,
)
from janusgraph_tpu.observability.metrics_core import (
    BUCKET_BOUNDS,
    Counter,
    Gauge,
    Histogram,
    TelemetryRegistry,
    Timer,
)
from janusgraph_tpu.observability.profiler import (
    DigestTable,
    ResourceLedger,
    accrue,
    accrue_wall,
    current_ledger,
    digest_table,
    flame_lines,
    ledger_scope,
)
from janusgraph_tpu.observability.slo import (
    SLOEngine,
    SLOSpec,
    slo_engine,
)
from janusgraph_tpu.observability.spans import (
    Span,
    TraceContext,
    Tracer,
    capture_scope,
    tracer,
)
from janusgraph_tpu.observability.stream import (
    STREAMS,
    Subscription,
    TelemetryBus,
    telemetry_bus,
)
from janusgraph_tpu.observability.timeline import (
    chrome_trace,
    render_run,
)
from janusgraph_tpu.observability.timeseries import (
    MetricsHistory,
    history,
)

#: process-wide registry (reference: MetricManager.INSTANCE);
#: `janusgraph_tpu.util.metrics.metrics` is THIS object
registry = TelemetryRegistry()

#: convenience alias: `with span("name", attr=...):` on the global tracer
span = tracer.span


def _slow_span_to_flight(event: dict) -> None:
    # the query digest (annotated onto the span by traversal execution)
    # rides along so recurring slow shapes group instead of appearing as
    # one-off offenders
    flight_recorder.record(
        "slow_span",
        name=event["name"],
        ms=event["ms"],
        trace_id=event.get("trace_id"),
        span_id=event.get("span_id"),
        digest=event.get("attrs", {}).get("digest"),
    )


# every span crossing the slow-op threshold also lands in the black box
tracer.on_slow = _slow_span_to_flight

__all__ = [
    "BUCKET_BOUNDS",
    "BundleWriter",
    "ClockOffsets",
    "Counter",
    "DigestTable",
    "FleetBundleStore",
    "FleetFederation",
    "FleetHistory",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "InstrumentedLock",
    "MetricsHistory",
    "ResourceLedger",
    "SLOEngine",
    "SLOSpec",
    "STREAMS",
    "SamplingProfiler",
    "Span",
    "StallWatchdog",
    "StructuredLogger",
    "Subscription",
    "TelemetryBus",
    "TelemetryRegistry",
    "Timer",
    "TraceContext",
    "Tracer",
    "accrue",
    "accrue_wall",
    "bundle_writer",
    "capture_scope",
    "chrome_trace",
    "current_ledger",
    "digest_table",
    "flame_from_artifact",
    "flame_lines",
    "flamediff",
    "fleet_default_specs",
    "flight_recorder",
    "get_logger",
    "history",
    "json_snapshot",
    "ledger_scope",
    "merge_incident_events",
    "merge_series",
    "merge_windows",
    "prometheus_text",
    "registry",
    "render_run",
    "replica_name",
    "sampling_profiler",
    "set_replica",
    "slo_engine",
    "span",
    "telemetry_bus",
    "tracer",
    "watchdog",
]
