"""Black-box flight recorder: a bounded ring of salient events.

Counters say HOW OFTEN the self-healing paths fire; the flight recorder
says WHAT HAPPENED, IN ORDER — the reconstructable incident timeline the
chaos engine (PR 3) made necessary. Producers append one small dict per
salient event; the ring is always on, cheap (dict + deque under one
lock), and bounded (``metrics.flight-buffer``).

Event taxonomy (the ``category`` field):

==================  =======================================================
``fault``           an injected chaos fault fired (storage/faults.py) —
                    the ``kind`` field includes the distributed kinds
                    ``shard_preempt`` / ``collective`` / ``halo_drop`` /
                    ``straggler``
``breaker``         a circuit breaker changed state (storage/circuit.py)
``retry_exhausted`` a backend_op retry guard gave up (storage/backend_op.py)
``torn_recovery``   TornCommitRecovery rolled a tx forward/back (core/txlog)
``checkpoint``      an OLAP checkpoint was written, or load fell back to
                    ``.prev`` (olap/checkpoint.py); sharded-format actions:
                    ``shard_save`` (manifest committed), ``shard_fallback``
                    (one slice restored from its ``.prev`` twin),
                    ``manifest_fallback`` (the whole checkpoint rolled to
                    ``manifest.json.prev`` — a torn write cost one
                    interval; olap/sharded_checkpoint.py)
``olap_resume``     an executor auto-resumed a preempted superstep run
                    (``executor`` field: tpu/cpu/sharded; sharded resumes
                    carry the triggering ``fault`` class and checkpoint
                    ``format``)
``shard_skew``      the sharded executor's straggler detector: modeled
                    slowest-shard/mean skew crossed the threshold or an
                    injected straggler fired (parallel/sharded.py)
``multihost``       jax.distributed cluster formation (init / init_ok /
                    init_failed; parallel/multihost.py)
``slow_span``       a span crossed metrics.slow-op-threshold-ms (fed by the
                    tracer's ``on_slow`` hook)
``server_error``    the query server hit an unhandled evaluation error
``health``          the /healthz status flipped ok -> degraded
``brownout``        the admission controller's graded-degradation ladder
                    changed rungs (server/admission.py BrownoutLadder;
                    fields: ``rung`` after the transition, ``direction``
                    enter/exit, ``reason``)
``spillover``       the OLTP->OLAP spillover planner acted
                    (olap/spillover.py; ``action``: ``promoted`` — a hot
                    digest crossed the promotion policy — or ``spilled``
                    — one traversal executed on the OLAP engine, with
                    ``digest``/``hops``/``overlay``/``wall_ms``/``total``)
``spillover_fallback``  a PROMOTED shape fell back to the row-by-row walk
                    (``digest`` + ``reason``: unsupported step, overlay
                    overflow, staleness breach, brownout refusal, count
                    overflow, or an internal error — fallback keeps the
                    query correct, the event keeps it visible)
``fleet``           serving-fleet lifecycle (server/fleet.py): ``join``,
                    ``rejoin``, ``dead`` (crash detection: probe/connect
                    failures), ``drain``/``drain_begin``/``drain_end``
                    (the graceful path, with handed-off/remaining session
                    counts), ``warmup`` (snapshot-cache hydration),
                    ``push_on`` / ``push_lost`` (the federation's
                    streaming transport negotiated with / lost to a
                    replica — observability/federation.py push mode). The
                    ``fault`` category's kind field includes the fleet
                    fault kinds ``replica_kill`` / ``replica_restart`` /
                    ``replica_partition``
``cdc_seal``        the durable CDC log sealed its tail into a segment
                    (storage/cdc.py; fields: ``seq``/``records``/
                    ``first_cursor``/``first_epoch``/``last_epoch``)
``cdc_replay``      a CDC replay was served or refused (``action``:
                    ``serve``, ``gap`` — cursor outside the retained
                    range, ``poison`` — an undecodable commit inside the
                    range, ``corrupt`` — a sealed segment failed its
                    digest, or ``caught_up`` — a promoting follower
                    proved itself current, the incident grammar's
                    closing phase)
``follower_promote``  a follower replica promoted to leader on leader
                    death (server/fleet.py CDCFollower.promote; fields:
                    ``replica``/``promote_ms``/``cursor``/``epoch``)
``slo_burn``        the SLO engine's burn-rate alert ladder transitioned
                    (observability/slo.py; fields: ``slo``/``kind``/
                    ``severity`` ok|ticket|page, ``direction`` enter/exit,
                    ``fast_burn``/``slow_burn``/``objective``) — a
                    page-severity burn also flips /healthz to degraded,
                    which dumps this ring via the existing edge trigger
``lock_convoy``     the stall watchdog caught a thread blocked on an
                    instrumented lock past ``server.watchdog-stall-s``
                    (observability/continuous.py; fields: ``lock``/
                    ``waiter``/``wait_s``/``owner``/``owner_stack`` —
                    the owner's stack snatched from the sampler ring —
                    and the ``wait_for`` edge [waiter, owner])
``stall``           a registered progress source (active requests,
                    supersteps, CDC pulls) reported active work whose
                    progress value did not change for the stall window
                    (fields: ``source``/``active``/``stuck_s``/
                    ``progress``); both watchdog events are
                    edge-triggered per key and each also captures a
                    forensics bundle
``bundle``          an anomaly forensics bundle was written
                    (observability/continuous.py BundleWriter; fields:
                    ``reason`` slo-page|stall|lock-convoy|server-error|
                    manual, ``path``)
``thread_error``    a background run loop caught an exception it would
                    previously have swallowed (the JG112 contract:
                    record before dying/continuing; fields: ``thread``/
                    ``error``)
==================  =======================================================

Dump triggers: an unhandled server error, the /healthz ok->degraded flip,
``GET /flight?dump=1``, and ``python -m janusgraph_tpu flight --dump``.
Dumps are JSON files under ``metrics.flight-dump-dir`` (default: the
system temp dir) named ``flight-<pid>-<n>.json``.

Every event carries a monotonic ``seq``, a wall-clock ``ts``, AND a
monotonic-clock ``mono`` stamp (dual timestamps, ISSUE 17): ``ts`` is
what humans and cross-replica merges read, ``mono`` is what in-process
interval math reads — wall clocks step under NTP, monotonic clocks
don't, and the fleet incident merge (observability/federation.py) uses
the pair to re-order events from replicas whose wall clocks disagree.
All OTHER fields are producer-supplied and deterministic for seeded
chaos plans, so two runs with one seed produce comparable event
sequences once clock fields are masked (the acceptance property
test_flight_trace asserts).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from janusgraph_tpu.observability.spans import _plain


class FlightRecorder:
    def __init__(self, capacity: int = 512, dump_dir: str = ""):
        self._ring: deque = deque(maxlen=capacity)
        self._counts: Dict[str, int] = {}
        self._seq = 0
        self._dumps = 0
        self.dump_dir = dump_dir
        self.last_dump_path: Optional[str] = None
        self.last_dump_ts: Optional[float] = None
        self._lock = threading.Lock()
        #: per-event hooks (the telemetry bus); called AFTER the ring
        #: lock is released, exceptions swallowed — same contract as
        #: MetricsHistory listeners
        self._listeners: List = []

    def add_listener(self, fn) -> None:
        """Register a per-event hook (the streaming telemetry bus);
        runs on the recording thread after the event lands."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def configure(
        self,
        capacity: Optional[int] = None,
        dump_dir: Optional[str] = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=capacity)
            if dump_dir is not None:
                self.dump_dir = dump_dir

    # -------------------------------------------------------------- recording
    def record(self, category: str, **fields) -> dict:
        """Append one event. Values are coerced to JSON-friendly host
        scalars (same contract as span attributes — never call this from
        jit-traced code; graphlint JG107). When the process carries a
        replica tag (observability/identity.py) every event is stamped
        with it, so cross-replica incident timelines merge by `replica`."""
        from janusgraph_tpu.observability.identity import replica_name

        replica = replica_name()
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "mono": time.monotonic(),
                "category": category,
                **({"replica": replica} if replica else {}),
                **{k: _plain(v) for k, v in fields.items()},
            }
            self._ring.append(event)
            self._counts[category] = self._counts.get(category, 0) + 1
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # noqa: BLE001 - a listener must not kill recording
                pass
        return event

    @property
    def last_seq(self) -> int:
        """Sequence of the newest recorded event — the ``flight``
        stream's cursor position (``/watch/info``)."""
        with self._lock:
            return self._seq

    # -------------------------------------------------------------- querying
    def events(self, category: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = [dict(e) for e in self._ring]
        if category is not None:
            evs = [e for e in evs if e["category"] == category]
        return evs

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def health_block(self) -> dict:
        """The compact summary /healthz embeds under ``flight``."""
        with self._lock:
            return {
                "occupancy": len(self._ring),
                "capacity": self._ring.maxlen or 0,
                "last_dump": self.last_dump_path,
                "counts": dict(self._counts),
            }

    def snapshot(self) -> dict:
        """The full ``GET /flight`` payload."""
        with self._lock:
            return {
                "occupancy": len(self._ring),
                "capacity": self._ring.maxlen or 0,
                "total_recorded": self._seq,
                "last_dump": self.last_dump_path,
                "last_dump_ts": self.last_dump_ts,
                "counts": dict(self._counts),
                "events": [dict(e) for e in self._ring],
            }

    # ---------------------------------------------------------------- dumping
    def dump(self, reason: str = "manual", path: Optional[str] = None) -> Optional[str]:
        """Write the ring to a JSON file and return its path. Failures
        return None instead of raising: the recorder dumps on the way DOWN
        (unhandled server errors, health flips) and must never turn an
        incident into a second one."""
        with self._lock:
            self._dumps += 1
            payload = {
                "dumped_at": time.time(),
                "reason": reason,
                "pid": os.getpid(),
                "total_recorded": self._seq,
                "counts": dict(self._counts),
                "events": [dict(e) for e in self._ring],
            }
            n = self._dumps
            directory = self.dump_dir or tempfile.gettempdir()
        if path is None:
            path = os.path.join(directory, f"flight-{os.getpid()}-{n}.json")
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        from janusgraph_tpu.observability import registry

        registry.counter("flight.dumps").inc()
        with self._lock:
            self.last_dump_path = path
            self.last_dump_ts = payload["dumped_at"]
        return path

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._counts.clear()
            self._seq = 0
            self._dumps = 0
            self.last_dump_path = None
            self.last_dump_ts = None
            self._listeners.clear()


#: process-wide recorder; every producer site appends here and
#: ``GET /flight`` / `janusgraph_tpu flight` read it back
recorder = FlightRecorder()
