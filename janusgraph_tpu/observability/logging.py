"""Structured JSON logging with ambient trace correlation.

One-line-JSON log records that auto-inject ``trace_id``/``span_id`` from
the tracer's contextvar, so a grep for one trace id walks the same
incident across the query server, the retry guard, the circuit breaker,
and the chaos engine — the textual twin of the span tree.

Records ALWAYS land in a bounded in-process ring (``recent()``: tests and
the flight-recorder post-mortem read it); they are written to a stream
only once one is configured (``metrics.structured-logging=true`` wires
``sys.stderr`` at graph open, or call :func:`configure` directly). The
default is ring-only so library users and the test suite don't get
stderr noise from every absorbed retry.

Host-only like every other telemetry call: emitting from jit-traced code
records once per compile and coerces traced values (graphlint JG107).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from janusgraph_tpu.observability.spans import _plain, tracer

_RING_LIMIT = 256

_lock = threading.Lock()
_ring: deque = deque(maxlen=_RING_LIMIT)
_stream = None
_loggers: Dict[str, "StructuredLogger"] = {}


def configure(stream=None, ring_size: Optional[int] = None) -> None:
    """Set (or clear, with None) the output stream; optionally resize the
    in-process ring."""
    global _stream, _ring
    with _lock:
        _stream = stream
        if ring_size is not None and ring_size != _ring.maxlen:
            _ring = deque(_ring, maxlen=ring_size)


def recent(level: Optional[str] = None) -> List[dict]:
    with _lock:
        records = [dict(r) for r in _ring]
    if level is not None:
        records = [r for r in records if r["level"] == level]
    return records


def reset() -> None:
    with _lock:
        _ring.clear()


class StructuredLogger:
    """Named emitter. ``info/warning/error(event, **fields)`` builds one
    flat JSON record: ts, level, logger, event, trace/span ids (when a
    span is ambient), then the caller's fields."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def _emit(self, level: str, event: str, fields: dict) -> dict:
        from janusgraph_tpu.observability.identity import replica_name

        record = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "event": event,
        }
        replica = replica_name()
        if replica:
            # fleet deployments tag every record with the producing
            # replica so one grep walks an incident across the fleet
            record["replica"] = replica
        span = tracer.current()
        if span is not None:
            record["trace_id"] = f"{span.trace_id:016x}"
            record["span_id"] = f"{span.span_id:016x}"
        for k, v in fields.items():
            record[k] = _plain(v)
        with _lock:
            _ring.append(record)
            stream = _stream
        if stream is not None:
            try:
                stream.write(json.dumps(record, default=str) + "\n")
            except (OSError, ValueError):
                pass  # a dead stream must not fail the operation being logged
        return record

    def info(self, event: str, **fields) -> dict:
        return self._emit("info", event, fields)

    def warning(self, event: str, **fields) -> dict:
        return self._emit("warning", event, fields)

    def error(self, event: str, **fields) -> dict:
        return self._emit("error", event, fields)


def get_logger(name: str) -> StructuredLogger:
    logger = _loggers.get(name)
    if logger is None:
        with _lock:
            logger = _loggers.setdefault(name, StructuredLogger(name))
    return logger
