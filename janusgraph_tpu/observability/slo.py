"""Declarative SLOs + multi-window burn-rate alerting over the history.

The Google-SRE burn-rate discipline, evaluated entirely in-process over
the :mod:`~janusgraph_tpu.observability.timeseries` window ring:

- an **SLO spec** (:class:`SLOSpec`) declares an objective over one of
  three signal kinds the stack already measures:

  ``availability``  good/bad from two counters — by default the admission
                    plane's ``server.admission.admitted`` vs ``.shed``
                    (PR 10): the non-shed fraction of arriving requests.
  ``latency``       the fraction of requests under a per-window threshold,
                    from timer bucket deltas. The threshold is explicit
                    (``threshold_ms``) or **priced**: per-digest-class
                    request timers (``server.request.digest.<digest>``)
                    are each held to ``price_factor x`` the digest's
                    measured mean cost from the admission price book
                    (PR 5/12) — an expensive analytical shape is allowed
                    its measured cost, a point-read is not.
  ``freshness``     a staleness gauge vs a bound — by default the OLAP
                    spillover snapshot's write-staleness
                    (``olap.spillover.staleness``, the delta-CSR signal
                    ROADMAP #4 will inherit).

- the **burn rate** is ``error_rate / error_budget`` with
  ``error_budget = 1 - objective``: burn 1.0 spends the budget exactly at
  the objective's horizon; burn 14.4 spends a 30-day budget in 2 days —
  the classic page threshold. Each spec is evaluated over a FAST and a
  SLOW window pair (counts of history windows) and alerts only when BOTH
  exceed the threshold — the fast window gives reaction time, the slow
  window vetoes blips.

- the **alert ladder** is hysteretic like the brownout ladder: severity
  ``ok -> ticket -> page`` enters when both windows burn past the rung's
  threshold and exits only after ``clear_windows`` consecutive
  evaluations below ``exit_factor x`` that threshold. Every transition is
  a flight ``slo_burn`` event and the per-spec gauges
  ``observability.slo.<name>.{burn_fast,burn_slow,severity}`` track the
  state (spec names are a small declared set — bounded cardinality).

- a page-severity burn makes ``/healthz`` report ``degraded``, which
  rides the existing ok->degraded edge trigger: the flight ring is on
  disk before anyone asks what happened.

Everything is deterministic on a fake clock: evaluation is driven by
:meth:`MetricsHistory.sample` (the engine registers as a listener), so a
test that feeds synthetic traffic and calls ``sample()`` N times gets a
byte-stable alert sequence.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from janusgraph_tpu.observability.timeseries import (
    MetricsHistory,
    bucket_upper_index,
)

SEV_OK = "ok"
SEV_TICKET = "ticket"
SEV_PAGE = "page"

#: per-digest-class request timer prefix (server/server.py records one
#: timer per price-book digest — bounded by the top-K-evicted book)
DIGEST_TIMER_PREFIX = "server.request.digest."


@dataclass
class SLOSpec:
    """One declarative objective. ``kind`` selects the signal:

    - ``availability``: ``good_counter``/``bad_counter`` deltas.
    - ``latency``: ``metric`` timer's under-threshold fraction; with
      ``metric=""`` the per-digest-class timers are evaluated jointly,
      each priced at ``price_factor x`` its book mean (floored at
      ``threshold_ms``).
    - ``freshness``: ``gauge`` vs ``max_staleness`` (mean over the
      window; burn = staleness / bound).
    """

    name: str
    kind: str  # availability | latency | freshness
    objective: float = 0.999
    # availability
    good_counter: str = "server.admission.admitted"
    bad_counter: str = "server.admission.shed"
    # latency
    metric: str = ""
    threshold_ms: float = 250.0
    price_factor: float = 4.0
    # freshness
    gauge: str = "olap.spillover.staleness"
    max_staleness: float = 10_000.0
    # burn windows + ladder
    fast_windows: int = 3
    slow_windows: int = 36
    page_burn: float = 14.4
    ticket_burn: float = 6.0
    exit_factor: float = 0.9
    clear_windows: int = 2

    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)


@dataclass
class _AlertState:
    severity: str = SEV_OK
    clear_streak: int = 0
    fast_burn: float = 0.0
    slow_burn: float = 0.0
    entered_seq: int = 0
    transitions: int = 0
    detail: dict = field(default_factory=dict)


def default_specs(
    availability_objective: float = 0.999,
    latency_objective: float = 0.99,
    latency_threshold_ms: float = 250.0,
    freshness_max_staleness: float = 10_000.0,
    fast_windows: int = 3,
    slow_windows: int = 36,
    page_burn: float = 14.4,
    ticket_burn: float = 6.0,
) -> List[SLOSpec]:
    """The stock spec set the server installs (``metrics.slo-*`` keys)."""
    common = dict(
        fast_windows=fast_windows, slow_windows=slow_windows,
        page_burn=page_burn, ticket_burn=ticket_burn,
    )
    return [
        SLOSpec(
            name="availability", kind="availability",
            objective=availability_objective, **common,
        ),
        SLOSpec(
            name="latency", kind="latency", objective=latency_objective,
            threshold_ms=latency_threshold_ms, **common,
        ),
        SLOSpec(
            name="olap_freshness", kind="freshness",
            objective=latency_objective,
            max_staleness=freshness_max_staleness, **common,
        ),
    ]


class SLOEngine:
    """Evaluates every spec once per history window; owns alert state.

    ``price_book_fn`` returns the active DigestTable used to price
    per-digest latency thresholds (None = unpriced, the flat
    ``threshold_ms`` applies to every class)."""

    def __init__(
        self,
        history: MetricsHistory,
        specs: Optional[List[SLOSpec]] = None,
        price_book_fn=None,
    ):
        self.history = history
        self.specs: List[SLOSpec] = list(specs or [])
        self.price_book_fn = price_book_fn
        self._states: Dict[str, _AlertState] = {}
        self._lock = threading.Lock()
        self._events = 0

    # ------------------------------------------------------------- lifecycle
    def install(self) -> "SLOEngine":
        """Register on the history's per-window hook (idempotent)."""
        self.history.add_listener(self._on_window)
        return self

    def uninstall(self) -> None:
        self.history.remove_listener(self._on_window)

    def _on_window(self, _window: dict) -> None:
        self.evaluate()

    def reset(self) -> None:
        with self._lock:
            self._states.clear()
            self._events = 0

    # ------------------------------------------------------------ evaluation
    def _rates(self, spec: SLOSpec, windows: List[dict]) -> tuple:
        """(bad, total) over a window slice for one spec."""
        if spec.kind == "availability":
            good = bad = 0
            for w in windows:
                good += w["counters"].get(spec.good_counter, 0)
                bad += w["counters"].get(spec.bad_counter, 0)
            return float(bad), float(good + bad)
        if spec.kind == "latency":
            if spec.metric:
                thresholds = {spec.metric: spec.threshold_ms}
            else:
                thresholds = self._digest_thresholds(spec, windows)
            bad = total = 0
            for name, threshold_ms in thresholds.items():
                # timers store nanoseconds; observations in buckets whose
                # upper bound exceeds the threshold MAY exceed it — exact
                # to the log2 ladder's 2x resolution, and deterministic
                cut = bucket_upper_index(threshold_ms * 1e6)
                for w in windows:
                    s = w["series"].get(name)
                    if s is None:
                        continue
                    total += s["count"]
                    bad += sum(s["buckets"][cut:])
            return float(bad), float(total)
        if spec.kind == "freshness":
            vals = [
                w["gauges"][spec.gauge]
                for w in windows if spec.gauge in w["gauges"]
            ]
            if not vals:
                return 0.0, 0.0
            # burn = mean staleness / bound, scaled through the budget so
            # "staleness at the bound" burns at exactly 1/budget (page-
            # worthy): the freshness objective is a hard ceiling, not a
            # fraction of requests
            mean = sum(vals) / len(vals)
            over = mean / max(spec.max_staleness, 1e-9)
            return over * spec.error_budget(), 1.0
        raise ValueError(f"unknown SLO kind {spec.kind!r}")

    def _digest_thresholds(
        self, spec: SLOSpec, windows: List[dict]
    ) -> Dict[str, float]:
        """Per-digest-class thresholds priced from the price book: each
        ``server.request.digest.<digest>`` timer seen in the slice is held
        to ``price_factor x`` its measured mean cost, floored at the flat
        ``threshold_ms`` so cheap shapes keep a sane bound."""
        names = set()
        for w in windows:
            for n in w["series"]:
                if n.startswith(DIGEST_TIMER_PREFIX):
                    names.add(n)
        book = self.price_book_fn() if self.price_book_fn else None
        out: Dict[str, float] = {}
        for n in names:
            digest = n[len(DIGEST_TIMER_PREFIX):]
            priced = book.mean_cost_ms(digest) if book is not None else None
            out[n] = max(
                spec.threshold_ms,
                spec.price_factor * priced if priced else 0.0,
            )
        return out

    def _burn(self, spec: SLOSpec, windows: List[dict]) -> float:
        bad, total = self._rates(spec, windows)
        if total <= 0:
            return 0.0
        return (bad / total) / spec.error_budget()

    def evaluate(self) -> List[dict]:
        """One evaluation pass over every spec; returns the current alert
        snapshot (also the /healthz ``slo`` block's ``alerts``)."""
        from janusgraph_tpu.observability import registry

        slow = self.history.windows(
            max((s.slow_windows for s in self.specs), default=0)
        )
        out = []
        for spec in self.specs:
            fast_burn = self._burn(spec, slow[-spec.fast_windows:])
            slow_burn = self._burn(spec, slow[-spec.slow_windows:])
            with self._lock:
                st = self._states.setdefault(spec.name, _AlertState())
                st.fast_burn = round(fast_burn, 4)
                st.slow_burn = round(slow_burn, 4)
                self._step(spec, st, fast_burn, slow_burn)
                out.append(self._snapshot_one(spec, st))
            sev_val = {
                SEV_OK: 0.0, SEV_TICKET: 1.0, SEV_PAGE: 2.0,
            }[st.severity]
            for suffix, value in (
                (".burn_fast", st.fast_burn),
                (".burn_slow", st.slow_burn),
                (".severity", sev_val),
            ):
                # graphlint: disable=JG110 -- spec names are a small declared set (bounded cardinality, never data-derived)
                registry.set_gauge(
                    "observability.slo." + spec.name + suffix, value
                )
        return out

    def _step(
        self, spec: SLOSpec, st: _AlertState, fast: float, slow: float
    ) -> None:
        """Hysteretic severity ladder (lock held). Enter a rung when BOTH
        windows burn past its threshold; exit one rung after
        ``clear_windows`` consecutive evaluations below ``exit_factor x``
        the CURRENT rung's threshold."""
        both = min(fast, slow)
        target = st.severity
        if both >= spec.page_burn:
            target = SEV_PAGE
        elif both >= spec.ticket_burn and st.severity == SEV_OK:
            target = SEV_TICKET
        if target != st.severity and _rank(target) > _rank(st.severity):
            self._transition(spec, st, target, "enter", fast, slow)
            st.clear_streak = 0
            return
        if st.severity == SEV_OK:
            st.clear_streak = 0
            return
        rung_burn = (
            spec.page_burn if st.severity == SEV_PAGE else spec.ticket_burn
        )
        if both < rung_burn * spec.exit_factor:
            st.clear_streak += 1
            if st.clear_streak >= spec.clear_windows:
                lower = (
                    SEV_TICKET if st.severity == SEV_PAGE else SEV_OK
                )
                self._transition(spec, st, lower, "exit", fast, slow)
                st.clear_streak = 0
        else:
            st.clear_streak = 0

    def _transition(
        self, spec, st: _AlertState, severity: str, direction: str,
        fast: float, slow: float,
    ) -> None:
        from janusgraph_tpu.observability import (
            flight_recorder,
            get_logger,
            registry,
        )

        st.severity = severity
        st.transitions += 1
        self._events += 1
        registry.counter("observability.slo.transitions").inc()
        flight_recorder.record(
            "slo_burn",
            slo=spec.name, kind=spec.kind, severity=severity,
            direction=direction,
            fast_burn=round(fast, 4), slow_burn=round(slow, 4),
            objective=spec.objective,
        )
        get_logger("observability.slo").warning(
            "slo-burn-transition",
            slo=spec.name, severity=severity, direction=direction,
            fast_burn=round(fast, 4), slow_burn=round(slow, 4),
        )

    # -------------------------------------------------------------- queries
    def _snapshot_one(self, spec: SLOSpec, st: _AlertState) -> dict:
        return {
            "name": spec.name,
            "kind": spec.kind,
            "objective": spec.objective,
            "severity": st.severity,
            "fast_burn": st.fast_burn,
            "slow_burn": st.slow_burn,
            "fast_windows": spec.fast_windows,
            "slow_windows": spec.slow_windows,
            "transitions": st.transitions,
        }

    def snapshot(self) -> dict:
        """The /healthz ``slo`` block."""
        with self._lock:
            alerts = [
                self._snapshot_one(spec, self._states[spec.name])
                for spec in self.specs
                if spec.name in self._states
            ]
        paging = [a["name"] for a in alerts if a["severity"] == SEV_PAGE]
        return {
            "specs": len(self.specs),
            "evaluated": len(alerts),
            "paging": paging,
            "worst": max(
                (a["severity"] for a in alerts),
                key=_rank, default=SEV_OK,
            ),
            "alerts": alerts,
        }

    def paging(self) -> bool:
        """True while any spec sits at page severity — /healthz folds
        this into its degraded verdict (and therefore the flight-dump
        edge trigger)."""
        with self._lock:
            return any(
                s.severity == SEV_PAGE for s in self._states.values()
            )


def _rank(sev: str) -> int:
    return {SEV_OK: 0, SEV_TICKET: 1, SEV_PAGE: 2}[sev]


#: process-wide engine over the process-wide history; the server installs
#: the stock specs at start() (metrics.slo-* keys) and /healthz reads it
from janusgraph_tpu.observability.timeseries import history as _history

slo_engine = SLOEngine(_history)
