"""TPU executor: jit-compiled BSP supersteps over device-resident CSR.

This is the north-star path (BASELINE.json): the reference's per-superstep
full-store rescan + concurrent-hashmap message buffers
(reference: FulgoraGraphComputer.java:210-230, FulgoraVertexMemory.java:41)
collapse into: CSR arrays resident in HBM + one compiled superstep =
gather (message per edge) -> segment-reduce (combine at destination) ->
elementwise apply. All shapes are static; the superstep index and the global
aggregators flow through as traced scalars, so ONE compilation (per combiner
monoid) serves every iteration. Termination is checked on host from the
reduced metrics — the only per-superstep host<->device traffic is that
handful of scalars.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import numpy as np

from janusgraph_tpu.olap.csr import CSRGraph
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    Memory,
    VertexProgram,
)


class _DeviceGraph:
    """CSR arrays on device + static metadata. Presents the same interface
    programs use (num_vertices / local_num_vertices / out_degree / ...)."""

    def __init__(self, csr: CSRGraph, jnp):
        self.num_vertices = csr.num_vertices
        self.local_num_vertices = csr.num_vertices
        self.global_offset = 0
        self.num_edges = csr.num_edges
        self.active = jnp.ones(csr.num_vertices)
        self.out_degree = jnp.asarray(csr.out_degree, dtype=jnp.float32)
        self.in_src = jnp.asarray(csr.in_src)
        self.in_dst_seg = jnp.asarray(_segment_ids(csr.in_indptr, csr.num_edges))
        self.out_dst = jnp.asarray(csr.out_dst)
        self.out_src_seg = jnp.asarray(_segment_ids(csr.out_indptr, csr.num_edges))
        self.in_edge_weight = (
            jnp.asarray(csr.in_edge_weight)
            if csr.in_edge_weight is not None
            else None
        )
        self.out_edge_weight = (
            jnp.asarray(csr.out_edge_weight)
            if csr.out_edge_weight is not None
            else None
        )


def _segment_ids(indptr: np.ndarray, m: int) -> np.ndarray:
    """indptr -> per-edge destination segment ids (repeat encoding)."""
    return np.repeat(
        np.arange(len(indptr) - 1, dtype=np.int32), np.diff(indptr)
    )[:m]


def _segment_reduce(jnp, op: str, data, segment_ids, num_segments: int):
    import jax

    if op == Combiner.SUM:
        return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    if op == Combiner.MIN:
        return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


class TPUExecutor:
    """Single-device executor. The sharded (mesh) executor lives in
    janusgraph_tpu/parallel/."""

    def __init__(self, csr: CSRGraph, use_pallas: bool = False):
        import jax
        import jax.numpy as jnp

        self.jax = jax
        self.jnp = jnp
        self.csr = csr
        self.g = _DeviceGraph(csr, jnp)
        self.use_pallas = use_pallas
        self._compiled: Dict[str, object] = {}

    # ------------------------------------------------------------ superstep
    def _superstep_fn(self, program: VertexProgram, op: str):
        """Build (and cache) the jitted superstep for one combiner monoid."""
        key = op
        if key in self._compiled:
            return self._compiled[key]

        jnp = self.jnp
        g = self.g
        n = g.local_num_vertices
        identity = Combiner.IDENTITY[op]

        def aggregate(outgoing, src_idx, dst_seg, weight):
            msgs = outgoing[src_idx]
            if program.edge_transform == EdgeTransform.MUL_WEIGHT and weight is not None:
                msgs = msgs * (weight[:, None] if msgs.ndim == 2 else weight)
            elif program.edge_transform == EdgeTransform.ADD_WEIGHT and weight is not None:
                msgs = msgs + (weight[:, None] if msgs.ndim == 2 else weight)
            return _segment_reduce(jnp, op, msgs, dst_seg, n)

        def superstep(state, superstep_idx, memory_in):
            outgoing = program.message(state, superstep_idx, g, jnp)
            agg = aggregate(outgoing, g.in_src, g.in_dst_seg, g.in_edge_weight)
            if program.undirected:
                rev = aggregate(
                    outgoing, g.out_dst, g.out_src_seg, g.out_edge_weight
                )
                if op == Combiner.SUM:
                    agg = agg + rev
                elif op == Combiner.MIN:
                    agg = jnp.minimum(agg, rev)
                else:
                    agg = jnp.maximum(agg, rev)
            # vertices with no in-edges hold the identity, matching the CPU
            # oracle's "no message received" semantics
            new_state, metrics = program.apply(
                state, agg, superstep_idx, memory_in, g, jnp
            )
            return new_state, {k: v for k, (_o, v) in metrics.items()}

        fn = self.jax.jit(superstep)
        self._compiled[key] = fn
        return fn

    # ------------------------------------------------------------------ run
    def run(self, program: VertexProgram, sync_every: int = 1) -> Dict[str, np.ndarray]:
        """Run to termination.

        `sync_every`: how often (in supersteps) the host fetches the global
        aggregators to evaluate `terminate`. Between syncs everything —
        state, aggregators, the superstep counter — stays on device and the
        host just enqueues work, so per-step host<->device latency (which
        can be tens of ms through a tunneled PJRT link) is amortized.
        Programs may run up to sync_every-1 supersteps past their stop
        condition; supersteps are idempotent at fixpoint for all monoid
        programs, so results are unchanged.
        """
        jnp = self.jnp
        memory = Memory()
        state, init_metrics = program.setup(self.g, jnp)
        memory.reduce_in(init_metrics)
        memory.superstep = 0

        # device-resident aggregators: no H2D after this point
        device_memory = {
            k: jnp.asarray(v, dtype=jnp.float32) for k, v in memory.values.items()
        }
        steps_done = 0
        for step in range(program.max_iterations):
            op = program.combiner_for(step)
            fn = self._superstep_fn(program, op)
            state, metrics = fn(
                state, jnp.asarray(step, dtype=jnp.int32), device_memory
            )
            device_memory = {
                k: metrics.get(k, device_memory.get(k)) for k in
                set(device_memory) | set(metrics)
            }
            steps_done += 1
            last = step == program.max_iterations - 1
            if steps_done % sync_every == 0 or last:
                host_vals = self.jax.device_get(metrics)  # one round trip
                memory.values = {k: float(v) for k, v in host_vals.items()}
                memory.superstep = steps_done
                if program.terminate(memory):
                    break
        return {k: np.asarray(v) for k, v in state.items()}

    # ------------------------------------------------------------ write-back
    def write_back(self, graph, result: Dict[str, np.ndarray], keys=None) -> None:
        """Persist compute-key arrays as vertex properties in batched txs
        (reference: FulgoraGraphComputer.java:359-437 VertexPropertyWriter)."""
        write_back(graph, self.csr, result, keys)


def write_back(graph, csr: CSRGraph, result: Dict[str, np.ndarray], keys=None, batch: int = 10_000) -> None:
    mgmt = graph.management()
    names = list(result.keys() if keys is None else keys)
    for name in names:
        if graph.schema_cache.get_by_name(name) is None:
            mgmt.make_property_key(name, float)
    vids = csr.vertex_ids
    for name in names:
        values = np.asarray(result[name], dtype=np.float64)
        for lo in range(0, len(vids), batch):
            tx = graph.new_transaction()
            for i in range(lo, min(lo + batch, len(vids))):
                v = tx.get_vertex(int(vids[i]))
                if v is not None:
                    v.property(name, float(values[i]))
            tx.commit()
