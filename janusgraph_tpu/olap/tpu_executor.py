"""TPU executor: jit-compiled BSP supersteps over device-resident CSR.

This is the north-star path (BASELINE.json): the reference's per-superstep
full-store rescan + concurrent-hashmap message buffers
(reference: FulgoraGraphComputer.java:210-230, FulgoraVertexMemory.java:41)
collapse into: CSR arrays resident in HBM + one compiled superstep =
gather (message per edge) -> segment-reduce (combine at destination) ->
elementwise apply. All shapes are static; the superstep index and the global
aggregators flow through as traced scalars, so ONE compilation (per combiner
monoid) serves every iteration. Termination is checked on host from the
reduced metrics — the only per-superstep host<->device traffic is that
handful of scalars.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Tuple

import numpy as np

from janusgraph_tpu.observability import registry, tracer
from janusgraph_tpu.olap.csr import CSRGraph
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    Memory,
    VertexProgram,
    apply_edge_transform,
)


class _DeviceGraph:
    """CSR arrays on device + static metadata. Presents the same interface
    programs use (num_vertices / local_num_vertices / out_degree / ...).

    Array fields are LAZY: each transfers to device on first access and is
    cached. The O(E) per-edge arrays are 2.1GB at scale 23 over a ~23MB/s
    tunnel — an ELL-strategy PageRank touches none of them (the ELL pack is
    the aggregation structure), so eager transfer of the full view was most
    of the measured 66-106s setup wall (VERDICT r3 weak #5)."""

    _LAZY = {
        "active": lambda csr, jnp: jnp.ones(csr.num_vertices),
        "out_degree": lambda csr, jnp: jnp.asarray(
            csr.out_degree, dtype=jnp.float32
        ),
        "in_degree": lambda csr, jnp: jnp.asarray(
            csr.in_degree, dtype=jnp.float32
        ),
        "in_src": lambda csr, jnp: jnp.asarray(csr.in_src),
        "in_dst_seg": lambda csr, jnp: jnp.asarray(
            _segment_ids(csr.in_indptr, csr.num_edges)
        ),
        "out_dst": lambda csr, jnp: jnp.asarray(csr.out_dst),
        "out_src_seg": lambda csr, jnp: jnp.asarray(
            _segment_ids(csr.out_indptr, csr.num_edges)
        ),
        "in_edge_weight": lambda csr, jnp: (
            jnp.asarray(csr.in_edge_weight)
            if csr.in_edge_weight is not None
            else None
        ),
        "out_edge_weight": lambda csr, jnp: (
            jnp.asarray(csr.out_edge_weight)
            if csr.out_edge_weight is not None
            else None
        ),
    }

    #: view fields a delta-fused run sources from the FUSED host view
    #: (degrees/activity patched by the overlay) instead of the base CSR
    _FUSED_FIELDS = frozenset(("active", "out_degree", "in_degree"))

    def __init__(self, csr: CSRGraph, jnp, host_view=None):
        self._csr = csr
        self._jnp = jnp
        #: delta-fused host view (olap/delta.FusedHostView) or None: the
        #: program-facing counts/degrees come from base+overlay while the
        #: base index arrays stay untouched for the base aggregation
        self._hv = host_view
        if host_view is not None:
            self.num_vertices = host_view.num_vertices
            self.local_num_vertices = host_view.local_num_vertices
            self.num_edges = host_view.num_edges
        else:
            self.num_vertices = csr.num_vertices
            self.local_num_vertices = csr.num_vertices
            self.num_edges = csr.num_edges
        self.global_offset = 0

    def __getattr__(self, name):
        # only reached when `name` is not an instance attribute yet
        fn = self._LAZY.get(name)
        if fn is None:
            raise AttributeError(name)
        if self._hv is not None and name in _DeviceGraph._FUSED_FIELDS:
            val = self._jnp.asarray(
                getattr(self._hv, name), dtype=self._jnp.float32
            )
        else:
            val = fn(self._csr, self._jnp)
        setattr(self, name, val)  # cache: next access skips __getattr__
        return val

    def spec(self, name):
        """jax.ShapeDtypeStruct for a view field WITHOUT transferring it —
        used by the view-usage discovery trace (`_used_view_keys`)."""
        import jax

        csr, np_ = self._csr, np
        # delta-fused views pad the vertex-shaped fields past the base
        # rows; local_num_vertices == csr.num_vertices otherwise
        nv = self.local_num_vertices
        shapes = {
            "active": ((nv,), np_.float32),
            "out_degree": ((nv,), np_.float32),
            "in_degree": ((nv,), np_.float32),
            "in_src": ((csr.num_edges,), csr.in_src.dtype),
            "in_dst_seg": ((csr.num_edges,), np_.int32),
            "out_dst": ((csr.num_edges,), csr.out_dst.dtype),
            "out_src_seg": ((csr.num_edges,), np_.int32),
            "in_w": ((csr.num_edges,), np_.float32),
            "out_w": ((csr.num_edges,), np_.float32),
        }
        shp, dt = shapes[name]
        return jax.ShapeDtypeStruct(shp, dt)


class _TracedView:
    """The graph view handed to program.message/apply inside a compiled
    superstep: static ints from the host-side view template, array fields
    resolved LAZILY from the traced `_graph_args` pytree leaves — only the
    fields a program actually reads are shipped as jit arguments (the
    discovery trace records accesses via `record`; see `_used_view_keys`)."""

    _KEYMAP = {"in_edge_weight": "in_w", "out_edge_weight": "out_w"}
    _FIELDS = frozenset(
        ("active", "out_degree", "in_degree", "in_src", "in_dst_seg",
         "out_dst", "out_src_seg", "in_edge_weight", "out_edge_weight")
    )

    def __init__(self, tmpl, arrs, record=None):
        self.num_vertices = tmpl.num_vertices
        self.local_num_vertices = tmpl.local_num_vertices
        self.global_offset = tmpl.global_offset
        self.num_edges = tmpl.num_edges
        self._arrs = arrs
        self._rec = record

    def __getattr__(self, name):
        if name not in _TracedView._FIELDS:
            raise AttributeError(name)
        key = _TracedView._KEYMAP.get(name, name)
        if self._rec is not None:
            self._rec.add(key)
        # absent key: weights are legitimately None on unweighted graphs;
        # any other miss means discovery and execution disagree on the
        # access set, which _PackView-style drift checks should surface
        return self._arrs.get(key)


class _PackView:
    """ELLPack-shaped facade over traced bucket arrays (duck-typed for
    ell_aggregate: .buckets / .unpermute / .has_weight)."""

    __slots__ = ("buckets", "unpermute", "has_weight")

    def __init__(self, bucket_args, bucket_slots, unpermute, has_weight):
        if len(bucket_args) != len(bucket_slots):
            raise ValueError(
                f"graph-args bucket count {len(bucket_args)} != compiled "
                f"bucket metadata {len(bucket_slots)} (pack drift)"
            )
        self.buckets = [
            (b["idx"], b.get("w"), b.get("valid"), b.get("rowseg"), ns)
            for b, ns in zip(bucket_args, bucket_slots)
        ]
        self.unpermute = unpermute
        self.has_weight = has_weight


def _segment_ids(indptr: np.ndarray, m: int) -> np.ndarray:
    """indptr -> per-edge destination segment ids (repeat encoding)."""
    from janusgraph_tpu import native

    return native.segment_ids(indptr, m)


def _pytree_nbytes(tree) -> int:
    """Total bytes of the array leaves of a dict/list pytree. Shape
    arithmetic only (`.nbytes` is static metadata) — no device sync."""
    if isinstance(tree, dict):
        return sum(_pytree_nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(_pytree_nbytes(v) for v in tree)
    return int(getattr(tree, "nbytes", 0) or 0)


def _segment_reduce(jnp, op: str, data, segment_ids, num_segments: int):
    import jax

    if op == Combiner.SUM:
        return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    if op == Combiner.MIN:
        return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


class TPUExecutor:
    """Single-device executor. The sharded (mesh) executor lives in
    janusgraph_tpu/parallel/.

    `strategy` selects the aggregation kernel (janusgraph_tpu/olap/kernels.py):
      - "ell"     degree-bucketed ELLPACK gather + dense reduce
                  (scatter-free, all monoids)
      - "hybrid"  exact-width ELL torso + chunked CSR tail for hubs
                  (bitwise-equal to "ell", pad ratio ~1)
      - "segment" XLA gather + segment-reduce
      - "pallas"  Pallas sorted-segment-sum kernel (SUM monoid; other
                  monoids fall back to "ell")
      - "auto"    (default) the profiler-driven autotuner picks among
                  ell/hybrid/segment from the degree histogram + device
                  roofline (olap/autotune.py; decision recorded in
                  run_info["autotune"])
    """

    def __init__(
        self,
        csr: CSRGraph,
        use_pallas: bool = False,
        strategy: str = "auto",
        ell_max_capacity: int = None,
        frontier: str = "auto",
        ell_auto_bytes: int = None,
        ell_auto_pad: float = None,
        channel_cache_size: int = None,
        frontier_cc_min_edges: int = None,
        frontier_f_min: int = None,
        frontier_e_min: int = None,
        frontier_tier_growth: int = None,
        autotune: bool = None,
        hub_cutoff: int = None,
        tail_chunk: int = None,
        autotune_min_gain: float = None,
        autotune_max_tiers: int = None,
        autotune_persist: bool = None,
        features_dim_tier: int = None,
        delta=None,
    ):
        import jax
        import jax.numpy as jnp

        self.jax = jax
        self.jnp = jnp
        self.csr = csr
        self.ell_max_capacity = ell_max_capacity  # computer.ell-max-capacity
        # delta-CSR overlay (olap/delta.OverlayView): supersteps consume
        # the pending write overlay FUSED with the base pack — base
        # aggregation over the untouched device-resident pack, delta
        # lanes merged through the same segment-combine contract
        self._delta = delta if (delta is not None and delta.depth) else None
        host_view = None
        if self._delta is not None:
            if csr.in_edge_weight is not None:
                raise ValueError(
                    "delta-fused runs support unfiltered weightless "
                    "snapshots only (the change capture carries no "
                    "weight column)"
                )
            from janusgraph_tpu.olap.delta import FusedHostView

            host_view = FusedHostView(self._delta)
        self.g = _DeviceGraph(csr, jnp, host_view=host_view)
        # the overlay-free device view is kept across set_delta swaps so a
        # cached executor returning to a clean snapshot reuses the already
        # shipped base arrays instead of re-uploading them
        self._base_g = self.g if self._delta is None else None
        if strategy == "auto" and use_pallas:
            strategy = "pallas"
        if strategy not in ("auto", "ell", "hybrid", "segment", "pallas"):
            raise ValueError(f"unknown aggregation strategy: {strategy!r}")
        # computer.autotune-* — the profiler-driven tuner behind "auto"
        # (olap/autotune.py); explicit strategies bypass it but are still
        # recorded as a source="config" decision
        self._autotune_enabled = True if autotune is None else bool(autotune)
        self._hub_cutoff_cfg = hub_cutoff or None
        self._tail_chunk_cfg = tail_chunk or None
        self._autotune_min_gain = autotune_min_gain
        self._autotune_max_tiers = autotune_max_tiers
        # computer.autotune-persist: serialize the last measured record
        # next to the checkpoint path and feed it back into decide() on
        # the next executor lifetime (ROADMAP #2 leftover)
        self._autotune_persist = (
            True if autotune_persist is None else bool(autotune_persist)
        )
        self._measured_path = None
        # computer.features-dim-tier: forced padded feature-dim lane tier
        # for dense programs (0 = tier ladder); the current dense run's
        # padded dim also feeds the tuner's feature-dim input
        self._features_dim_tier = features_dim_tier or 0
        self._feature_dim_run = 0
        # decisions keyed (undirected, feature_dim) — a dense run's tier
        # changes the modeled message bytes, so it is its own decision
        self._autotune_decisions: Dict[Tuple, object] = {}
        if frontier not in ("auto", "off", "always"):
            raise ValueError(f"unknown frontier mode: {frontier!r}")
        # Frontier-compacted SSSP/BFS/CC (olap/frontier.py): the program
        # special-case, mirroring FulgoraGraphComputer.java:249-253
        self._frontier_cfg = frontier
        self._frontier_engine = None
        # computer.ell-auto-budget-bytes / ell-auto-pad /
        # channel-cache-size overrides (class attrs remain the defaults)
        if ell_auto_bytes is not None:
            self.ELL_AUTO_BYTES = ell_auto_bytes
        if ell_auto_pad is not None:
            self.ELL_AUTO_PAD = ell_auto_pad
        if channel_cache_size is not None:
            self.CHANNEL_CACHE_SIZE = channel_cache_size
        # computer.frontier-cc-min-edges / frontier-f-min / frontier-e-min
        if frontier_cc_min_edges is not None:
            self.FRONTIER_CC_MIN_EDGES = frontier_cc_min_edges
        self._frontier_f_min = frontier_f_min
        self._frontier_e_min = frontier_e_min
        # computer.frontier-tier-growth — tier ladder growth factor
        self._frontier_tier_growth = frontier_tier_growth
        # "auto" resolves lazily per edge view: an undirected program packs
        # in+out edges (~2x footprint), so the budget check must see the
        # view it will actually ship
        self._strategy_cfg = strategy
        self._auto_cache: Dict[Tuple, str] = {}
        # Pallas kernels interpret on CPU/virtual devices, compile on real
        # TPU (platform may be a tunneled plugin name like "axon" whose
        # device_kind still identifies the TPU generation)
        dev = jax.devices()[0]
        self._interpret = not (
            dev.platform == "tpu" or "tpu" in dev.device_kind.lower()
        )
        from collections import OrderedDict

        #: per-run execution record ({"path", "supersteps", "wall_s", ...});
        #: the executor-level analogue of the OLTP .profile() tree. Also
        #: published through the telemetry registry after every run:
        #: `registry.last_run("olap")` (observability/metrics_core.py)
        self.last_run_info: Dict[str, object] = {}
        #: bytes of the graph-argument pytree shipped to the last compiled
        #: dispatch (view fields + ELL buckets) — host-side arithmetic on
        #: static shapes, no device sync
        self._last_arg_bytes = 0
        self._compiled: Dict[str, object] = {}
        # per-variant kernel cost records ({"flops", "bytes_accessed",
        # "cost_source"}): harvested ONCE per compiled variant from the
        # lowered module's XLA cost analysis, host estimator otherwise
        # (observability/profiler.py roofline model)
        self._kernel_costs: Dict[Tuple, dict] = {}
        # view-field access sets per compiled variant (discovery trace);
        # None record = not discovering
        self._viewkeys: Dict[Tuple, frozenset] = {}
        self._view_record = None
        # (cache_key, op) -> {metric_key: combiner_op}, recorded as a side
        # effect of tracing the superstep body (apply declares each
        # aggregator's monoid inline; the fused path needs the full pytree
        # + identities BEFORE the first compiled dispatch)
        self._metric_ops: Dict[Tuple, Dict[str, str]] = {}
        self._ell_packs: Dict[bool, object] = {}
        self._hybrid_packs: Dict[bool, object] = {}
        # per-(strategy, orientation) row-destination vectors for the
        # dense tier's fused SDDMM pass (features/kernels row-dst builders)
        self._sddmm_rows_cache: Dict[Tuple, object] = {}
        self._channel_packs: "OrderedDict" = OrderedDict()
        self._segsum_plans: Dict[str, object] = {}

    def set_delta(self, delta) -> None:
        """Swap the pending-overlay view WITHOUT rebuilding the executor —
        the warm-submit executor-cache path (olap/computer.py): the base
        CSR, ELL/hybrid packs, compiled executables, and autotune
        decisions all survive across submits. A new overlay with the same
        lane signature reuses the compiled fused executable outright (the
        lanes ship as jit ARGUMENTS); a different signature compiles its
        own variant under the sig-keyed executable cache. ``None`` (or an
        empty view) returns the executor to the overlay-free base view."""
        delta = delta if (delta is not None and delta.depth) else None
        if delta is None:
            if self._delta is None:
                return
            self._delta = None
            if self._base_g is None:
                self._base_g = _DeviceGraph(self.csr, self.jnp)
            self.g = self._base_g
            return
        if self.csr.in_edge_weight is not None:
            raise ValueError(
                "delta-fused runs support unfiltered weightless "
                "snapshots only (the change capture carries no weight "
                "column)"
            )
        if delta.csr is not self.csr:
            raise ValueError(
                "overlay view was built over a different base snapshot "
                "— a cached executor only serves overlays of ITS base "
                "CSR (the snapshot cache invalidates on compaction)"
            )
        if self._base_g is None and self._delta is None:
            self._base_g = self.g
        from janusgraph_tpu.olap.delta import FusedHostView

        self._delta = delta
        self.g = _DeviceGraph(
            self.csr, self.jnp, host_view=FusedHostView(delta)
        )

    @staticmethod
    def ell_footprint(
        csr: CSRGraph, max_capacity: int = 1 << 14, undirected: bool = False
    ):
        """Estimate the ELL pack's device footprint WITHOUT building it:
        per-vertex slot count = next-pow2(degree) (capped, supernodes
        row-split at ~1x). Unweighted graphs ship idx (i32) only — padded
        slots read the identity through the sentinel; weighted graphs add
        weight + valid f32 matrices. Undirected programs pack BOTH
        orientations, so their estimate uses in+out degree. Computed from
        the degree histogram in one numpy pass."""
        deg = np.diff(csr.in_indptr).astype(np.int64)
        edges = csr.num_edges
        if undirected:
            deg = deg + np.diff(csr.out_indptr).astype(np.int64)
            edges *= 2
        caps = np.maximum(1, 1 << np.ceil(
            np.log2(np.maximum(deg, 1))
        ).astype(np.int64))
        slots = int(np.minimum(caps, max_capacity).sum())
        # row-split remainder of supernodes keeps ~1 slot per edge
        over = deg > max_capacity
        if over.any():
            slots += int((deg[over] - max_capacity).sum())
        per_slot = 12 if csr.in_edge_weight is not None else 4
        return {
            "slots": slots,
            "bytes": slots * per_slot,
            "pad_ratio": slots / max(1, edges),
        }

    #: HBM budget the auto strategy lets the ELL pack use (v5e lite has
    #: 16GB; leave room for state/messages/output + XLA scratch)
    ELL_AUTO_BYTES = 6 << 30
    ELL_AUTO_PAD = 3.0

    def _device_kind(self) -> str:
        return getattr(self.jax.devices()[0], "device_kind", "cpu")

    def _autotune_overrides(self) -> dict:
        """The computer.autotune-* / legacy-budget knobs, in the tuner's
        override vocabulary (None entries mean 'search')."""
        return {
            "hub_cutoff": self._hub_cutoff_cfg,
            "tail_chunk": self._tail_chunk_cfg,
            "min_gain": self._autotune_min_gain,
            "budget_bytes": self.ELL_AUTO_BYTES,
            "max_pad": self.ELL_AUTO_PAD,
            "f_min": self._frontier_f_min,
            "e_min": self._frontier_e_min,
            "max_tiers": self._autotune_max_tiers,
            "tier_growth": self._frontier_tier_growth,
        }

    def _autotune(self, undirected: bool, measured: dict = None):
        """The (cached) AutotuneDecision for one edge view (and, for dense
        runs, one feature tier). Deterministic given (graph stats, device
        kind, config, persisted measurement): olap/autotune.decide."""
        key = (undirected, self._feature_dim_run)
        decision = self._autotune_decisions.get(key)
        if decision is not None and measured is None:
            return decision
        from janusgraph_tpu.olap import autotune

        if measured is None and self._measured_path:
            # a prior executor lifetime's persisted record (computer.
            # autotune-persist): achieved bandwidth calibrates the model
            measured = autotune.load_measured(
                self._measured_path, shard_count=1
            )
        stats = autotune.GraphStats.from_csr(
            self.csr, undirected=undirected,
            max_capacity=self.ell_max_capacity or (1 << 14),
            tail_chunk=self._tail_chunk_cfg or 256,
        )
        ov = self._autotune_overrides()
        if self._strategy_cfg != "auto":
            ov["strategy"] = self._strategy_cfg
        if self._features_dim_tier:
            ov["feature_dim_tier"] = self._features_dim_tier
        decision = autotune.decide(
            stats, self._device_kind(), overrides=ov, measured=measured,
            feature_dim=self._feature_dim_run,
        )
        self._autotune_decisions[key] = decision
        return decision

    def _auto_strategy(self, undirected: bool) -> str:
        """'auto' resolution. With the tuner enabled (the default) this is
        the autotune decision — strategy chosen against the device roofline
        from the degree histogram (ISSUE 6 closes the PR 5 loop); the
        legacy footprint-budget heuristic remains as the fallback when
        computer.autotune=false (VERDICT r2 shape: ELL within budget,
        segment otherwise)."""
        if self._autotune_enabled:
            return self._autotune(undirected).strategy
        fp = self.ell_footprint(
            self.csr, self.ell_max_capacity or (1 << 14), undirected
        )
        if fp["bytes"] > self.ELL_AUTO_BYTES or fp["pad_ratio"] > self.ELL_AUTO_PAD:
            return "segment"
        return "ell"

    @property
    def strategy(self) -> str:
        """The configured strategy; 'auto' reports the directed-view
        resolution (display/back-compat)."""
        return self._base_strategy(False)

    def _base_strategy(self, undirected: bool) -> str:
        base = self._strategy_cfg
        if base == "auto":
            key = (undirected, self._feature_dim_run)
            base = self._auto_cache.get(key)
            if base is None:
                base = self._auto_strategy(undirected)
                self._auto_cache[key] = base
        return base

    def _edge_view(self, undirected: bool):
        """(src, dst, w) edge arrays for one orientation view — the single
        assembly shared by the pack builders and the sddmm row-dst
        builders, so their layouts can never disagree."""
        csr = self.csr
        src = csr.in_src.astype(np.int64)
        dst = _segment_ids(csr.in_indptr, csr.num_edges).astype(np.int64)
        w = csr.in_edge_weight
        if undirected:
            src = np.concatenate([src, csr.out_dst.astype(np.int64)])
            dst = np.concatenate([
                dst,
                _segment_ids(csr.out_indptr, csr.num_edges).astype(np.int64),
            ])
            w = (
                np.concatenate([w, csr.out_edge_weight])
                if w is not None
                else None
            )
        return src, dst, w

    def _ell_pack(self, undirected: bool):
        from janusgraph_tpu.olap.kernels import ELLPack

        pack = self._ell_packs.get(undirected)
        if pack is None:
            src, dst, w = self._edge_view(undirected)
            pack = ELLPack(
                src, dst, w, self.csr.num_vertices, **self._ell_kwargs()
            )
            pack.device_put(self.jnp)
            self._ell_packs[undirected] = pack
        return pack

    def _sddmm_rows(self, strategy: str, undirected: bool):
        """Row-destination vectors for the fused SDDMM pass, aligned with
        the strategy's pack layout (features/kernels builders); built once
        per (strategy, orientation) and kept device-resident."""
        from janusgraph_tpu.olap.features import kernels as fkernels

        key = (strategy, undirected)
        rows = self._sddmm_rows_cache.get(key)
        if rows is not None:
            return rows
        src, dst, _w = self._edge_view(undirected)
        cap = self.ell_max_capacity or (1 << 14)
        if strategy == "ell":
            host = fkernels.ell_row_dsts(
                src, dst, self.csr.num_vertices, max_capacity=cap
            )
            rows = [self.jnp.asarray(r) for r in host]
        else:
            pack = self._hybrid_pack(undirected)
            host = fkernels.hybrid_row_dsts(
                src, dst, self.csr.num_vertices,
                hub_cutoff=pack.hub_cutoff, tail_chunk=pack.tail_chunk,
                max_capacity=cap,
            )
            rows = {
                "torso": [self.jnp.asarray(r) for r in host["torso"]],
                "tail": [self.jnp.asarray(r) for r in host["tail"]],
            }
        self._sddmm_rows_cache[key] = rows
        return rows

    def _ell_kwargs(self):
        return (
            {"max_capacity": self.ell_max_capacity}
            if self.ell_max_capacity
            else {}
        )

    def _hybrid_pack(self, undirected: bool):
        """HybridPack for one edge view, with the tuner's (or configured)
        hub cutoff + tail chunk. Built and device-put once, like the ELL
        pack."""
        from janusgraph_tpu.olap.kernels import HybridPack

        pack = self._hybrid_packs.get(undirected)
        if pack is None:
            d = self._autotune(undirected)
            cutoff = self._hub_cutoff_cfg or d.hub_cutoff or 512
            chunk = self._tail_chunk_cfg or d.tail_chunk or 256
            src, dst, w = self._edge_view(undirected)
            pack = HybridPack(
                src, dst, w, self.csr.num_vertices,
                hub_cutoff=cutoff, tail_chunk=chunk, **self._ell_kwargs(),
            )
            pack.device_put(self.jnp)
            self._hybrid_packs[undirected] = pack
        return pack

    #: distinct EdgeChannel views kept device-resident at once; a long-lived
    #: executor answering ad-hoc traverse() queries would otherwise
    #: accumulate one O(E) pack per label-set forever
    CHANNEL_CACHE_SIZE = 8

    def _channel_pack(self, program: VertexProgram, name: str):
        """ELL pack for one named EdgeChannel (typed edge view). Built from
        the channel's filtered edge list; cached per channel VALUE (frozen
        dataclass) — names like 's0' recur across different programs on a
        reused executor and must not alias each other's packs. LRU-bounded;
        eviction also drops compiled supersteps that close over the pack."""
        from janusgraph_tpu.olap.csr import channel_edges
        from janusgraph_tpu.olap.kernels import ELLPack

        channel = program.edge_channels[name]
        pack = self._channel_packs.get(channel)
        if pack is not None:
            self._channel_packs.move_to_end(channel)
            return pack
        src, dst, w = channel_edges(self.csr, channel)
        pack = ELLPack(
            src, dst, w, self.csr.num_vertices, **self._ell_kwargs()
        )
        pack.device_put(self.jnp)
        self._channel_packs[channel] = pack
        while len(self._channel_packs) > self.CHANNEL_CACHE_SIZE:
            evicted, _ = self._channel_packs.popitem(last=False)
            self._compiled = {
                k: v for k, v in self._compiled.items()
                if not (len(k) >= 5 and k[4] == evicted)
            }
        return pack

    def _segsum_plan(self, orientation: str):
        from janusgraph_tpu.olap.kernels import make_segsum_plan

        plan = self._segsum_plans.get(orientation)
        if plan is None:
            csr = self.csr
            if orientation == "in":
                seg = _segment_ids(csr.in_indptr, csr.num_edges)
            else:
                seg = _segment_ids(csr.out_indptr, csr.num_edges)
            plan = make_segsum_plan(seg, csr.num_vertices)
            self._segsum_plans[orientation] = plan
        return plan

    def _resolve_strategy(self, op: str, undirected: bool = False) -> str:
        """The strategy actually used for a combiner monoid and edge view:
        auto resolves against the view's footprint; the pallas kernel is
        SUM-only, everything else falls back to ELL."""
        base = self._base_strategy(undirected)
        if base == "pallas" and op != Combiner.SUM:
            return "ell"
        return base

    def prewarm(self, program: VertexProgram) -> None:
        """Build + device-put the aggregation structures a program will use,
        so transfer cost is paid (and measurable) before the first run."""
        strategy = self._resolve_strategy(
            program.combiner, program.undirected
        )
        if strategy == "ell":
            self._ell_pack(program.undirected)
        elif strategy == "hybrid":
            self._hybrid_pack(program.undirected)
        elif strategy == "pallas":
            self._segsum_plan("in")
            if program.undirected:
                self._segsum_plan("out")

    # ------------------------------------------------------------ superstep
    def _used_view_keys(
        self, program: VertexProgram, op: str, channel=None,
        state=None, mem0=None,
    ):
        """Which view fields this compiled variant actually reads — learned
        from ONE abstract trace (eval_shape: no compile, no transfer; view
        leaves are ShapeDtypeStructs). Shipping only these cuts the s23
        device transfer from ~2.9GB to the aggregation structure + what the
        program touches (VERDICT r3 weak #5: setup dominated end-to-end).
        The same trace records each metric's combiner op (`_metric_ops`),
        so the fused path needs no second discovery pass."""
        jnp = self.jnp
        ch_val = program.edge_channels[channel] if channel is not None else None
        key = (
            program.cache_key(), op, self._strategy_cfg, ch_val,
            self._delta_sig(program),
        )
        used = self._viewkeys.get(key)
        if used is not None:
            return used
        g = self.g
        view = {
            k: g.spec(k)
            for k in ("active", "out_degree", "in_degree", "in_src",
                      "in_dst_seg", "out_dst", "out_src_seg")
        }
        if self.csr.in_edge_weight is not None:
            view["in_w"] = g.spec("in_w")
        if self.csr.out_edge_weight is not None:
            view["out_w"] = g.spec("out_w")
        args = {"view": view}
        strategy, pack = self._resolve_pack(program, op, channel)
        if strategy == "ell":
            args["ell"] = self._pack_args(pack)
            args["unpermute"] = pack.unpermute
        elif strategy == "hybrid":
            args["hyb"] = self._hybrid_args(pack)
        if getattr(program, "message_mode", None) == "sddmm" and strategy in (
            "ell", "hybrid"
        ):
            args["sddmm"] = self._sddmm_rows(strategy, program.undirected)
        if self._delta is not None:
            args["delta"] = self._delta.device_args(
                jnp, bool(program.undirected)
            )
        if state is None:
            # cold discovery (direct _graph_args call before any run):
            # setup just to learn the state/metric pytree shapes
            state, init_metrics = program.setup(g, jnp)
            mem0 = {
                k: jnp.asarray(v, dtype=jnp.float32)
                for k, (_o, v) in init_metrics.items()
            }
        abstract = self.jax.tree_util.tree_map(
            lambda a: self.jax.ShapeDtypeStruct(
                jnp.shape(a), jnp.result_type(a)
            ),
            (state, mem0),
        )
        rec = set()
        self._view_record = rec
        try:
            body = self._superstep_body(program, op, channel)
            self.jax.eval_shape(
                body, abstract[0], jnp.asarray(0, jnp.int32), abstract[1],
                args,
            )
        finally:
            self._view_record = None
        used = frozenset(rec)
        self._viewkeys[key] = used
        return used

    @staticmethod
    def _pack_args(pack):
        buckets = []
        for idx, w, valid, rowseg, _ns in pack.buckets:
            b = {"idx": idx}
            if w is not None:
                b["w"] = w
            if valid is not None:
                b["valid"] = valid
            if rowseg is not None:
                b["rowseg"] = rowseg
            buckets.append(b)
        return buckets

    @staticmethod
    def _hybrid_args(pack):
        """The hybrid pack's array pytree (shipped as jit arguments, like
        _pack_args for ELL — closing over the arrays would constant-fold
        them into the module)."""
        return {
            "torso": [dict(b) for b in pack.torso],
            "tail": [dict(b) for b in pack.tail],
            "unpermute": pack.unpermute,
        }

    def _graph_args(self, program: VertexProgram, op: str, channel: str = None):
        """The device-array pytree a compiled superstep consumes as an
        ARGUMENT. Closing over device arrays would embed them as constants
        in the lowered module — at s22 that is a >1GB HLO payload the
        tunneled remote-compile endpoint rejects outright (HTTP 413), and
        constant-folding it is where the pathological compile time went.
        Only view fields the variant actually reads are included (and thus
        transferred): see `_used_view_keys`."""
        g = self.g
        attr_of = {"in_w": "in_edge_weight", "out_w": "out_edge_weight"}
        view = {}
        for key in self._used_view_keys(program, op, channel):
            val = getattr(g, attr_of.get(key, key))
            if val is not None:
                view[key] = val
        args = {"view": view}
        strategy, pack = self._resolve_pack(program, op, channel)
        if strategy == "ell":
            args["ell"] = self._pack_args(pack)
            args["unpermute"] = pack.unpermute
        elif strategy == "hybrid":
            args["hyb"] = self._hybrid_args(pack)
        if getattr(program, "message_mode", None) == "sddmm" and strategy in (
            "ell", "hybrid"
        ):
            args["sddmm"] = self._sddmm_rows(strategy, program.undirected)
        if self._delta is not None:
            args["delta"] = self._delta.device_args(
                self.jnp, bool(program.undirected)
            )
        self._last_arg_bytes = _pytree_nbytes(args)
        return args

    def _delta_sig(self, program):
        """Static compile signature of the delta overlay for this
        program's edge view (part of every compiled-executable key), or
        None without an overlay. Raises when the overlay's lanes exceed
        the configured cell budget — the caller should have materialized
        instead of fusing."""
        if self._delta is None:
            return None
        sig = self._delta.sig(bool(program.undirected))
        if sig is None:
            raise ValueError(
                "delta overlay lanes exceed computer.delta-max-lane-cells"
                " — materialize the overlay instead of consuming it fused"
            )
        return sig

    def _resolve_pack(self, program: VertexProgram, op: str, channel: str = None):
        """(strategy, ELLPack-or-None) for one combiner monoid + edge view —
        the single source of truth shared by `_graph_args` (which ships the
        pack's arrays) and `_superstep_body` (which captures its static
        bucket metadata), so the two can never disagree on bucket count."""
        strategy = self._resolve_strategy(op, program.undirected)
        pack = None
        if channel is not None:
            strategy = "ell"
            pack = self._channel_pack(program, channel)
        elif strategy == "ell":
            pack = self._ell_pack(program.undirected)
        elif strategy == "hybrid":
            pack = self._hybrid_pack(program.undirected)
        return strategy, pack

    def _superstep_body(self, program: VertexProgram, op: str, channel: str = None):
        """Build the (un-jitted) superstep function for one combiner monoid
        (and, for channel-switching programs, one named edge channel —
        channel steps always aggregate over the channel's ELL pack). The
        returned function takes the graph-array pytree (`_graph_args`) as
        its final argument; only static metadata is captured by closure."""

        jnp = self.jnp
        n = self.g.local_num_vertices
        tmpl = self.g
        identity = Combiner.IDENTITY[op]
        # delta overlay: base aggregation runs over the base rows only
        # (the pack's sentinel is index n_base); the lanes merge after
        delta = self._delta
        nb = self.csr.num_vertices if delta is not None else n
        dmeta = None
        if delta is not None:
            dmeta = dict(
                delta.lanes(bool(program.undirected))["_meta"]
            )
        strategy, pack_meta = self._resolve_pack(program, op, channel)
        if strategy == "pallas":
            plans = [("in", self._segsum_plan("in"))]
            if program.undirected:
                plans.append(("out", self._segsum_plan("out")))
        elif strategy == "ell":
            bucket_slots = [b[4] for b in pack_meta.buckets]
            has_weight = pack_meta.has_weight
        # "hybrid": pack_meta (the HybridPack) is captured for its STATIC
        # metadata only (bucket widths/rows); arrays arrive via gargs

        def aggregate(outgoing, src_idx, dst_seg, weight):
            msgs = apply_edge_transform(
                jnp, outgoing[src_idx], weight,
                program.edge_transform, program.edge_transform_cols,
            )
            return _segment_reduce(jnp, op, msgs, dst_seg, nb)

        def pallas_aggregate(outgoing, gv):
            from janusgraph_tpu.olap.kernels import pallas_sorted_segment_sum

            def one(orientation, plan):
                if orientation == "in":
                    src_idx, weight = gv.in_src, gv.in_edge_weight
                else:
                    src_idx, weight = gv.out_dst, gv.out_edge_weight
                msgs = outgoing[src_idx]
                if program.edge_transform == EdgeTransform.MUL_WEIGHT and weight is not None:
                    msgs = msgs * weight
                elif program.edge_transform == EdgeTransform.ADD_WEIGHT and weight is not None:
                    msgs = msgs + weight
                return pallas_sorted_segment_sum(
                    msgs, plan, interpret=self._interpret
                )

            total = one(*plans[0])
            for orientation, plan in plans[1:]:
                total = total + one(orientation, plan)
            return total

        def superstep(state, superstep_idx, memory_in, gargs):
            gv = _TracedView(tmpl, gargs["view"], self._view_record)
            from janusgraph_tpu.olap.kernels import ell_aggregate

            full_out = program.message(state, superstep_idx, gv, jnp)
            # base aggregation consumes the base-row slice: the packs'
            # sentinel (index n_base) must keep reading the identity
            outgoing = full_out if delta is None else full_out[:nb]
            mode = getattr(program, "message_mode", None)
            if mode == "sddmm":
                # dense tier: fused SDDMM+SpMM — per-edge dot-attention
                # coefficients computed in the same gather pass
                from janusgraph_tpu.olap.features.kernels import (
                    sddmm_ell_aggregate,
                    sddmm_hybrid_aggregate,
                    sddmm_segment_aggregate,
                )

                if strategy == "ell":
                    pv = _PackView(
                        gargs["ell"], bucket_slots, gargs["unpermute"],
                        has_weight,
                    )
                    agg = sddmm_ell_aggregate(
                        jnp, pv, gargs["sddmm"], outgoing, op
                    )
                elif strategy == "hybrid":
                    from janusgraph_tpu.olap.kernels import HybridPackView

                    hv = HybridPackView(gargs["hyb"], pack_meta)
                    agg = sddmm_hybrid_aggregate(
                        jnp, hv, gargs["sddmm"], outgoing, op
                    )
                else:
                    agg = sddmm_segment_aggregate(
                        jnp, outgoing, gv.in_src, gv.in_dst_seg, n
                    )
            elif strategy == "ell":
                pv = _PackView(
                    gargs["ell"], bucket_slots, gargs["unpermute"], has_weight
                )
                agg = ell_aggregate(
                    jnp, pv, outgoing, op, program.edge_transform,
                    program.edge_transform_cols,
                )
            elif strategy == "hybrid":
                from janusgraph_tpu.olap.kernels import (
                    HybridPackView,
                    hybrid_aggregate,
                )

                hv = HybridPackView(gargs["hyb"], pack_meta)
                agg = hybrid_aggregate(
                    jnp, hv, outgoing, op, program.edge_transform,
                    program.edge_transform_cols,
                )
            elif strategy == "pallas" and outgoing.ndim == 1:
                agg = pallas_aggregate(outgoing, gv)
            else:
                agg = aggregate(
                    outgoing, gv.in_src, gv.in_dst_seg, gv.in_edge_weight
                )
                if program.undirected:
                    rev = aggregate(
                        outgoing, gv.out_dst, gv.out_src_seg, gv.out_edge_weight
                    )
                    if op == Combiner.SUM:
                        agg = agg + rev
                    elif op == Combiner.MIN:
                        agg = jnp.minimum(agg, rev)
                    else:
                        agg = jnp.maximum(agg, rev)
            if delta is not None:
                # fuse the overlay lanes over the base aggregate (SUM:
                # add - tombstone subtraction; MIN/MAX: dirty rows
                # re-aggregated from the live lane) — olap/delta.py
                from janusgraph_tpu.olap.delta import (
                    fused_delta_aggregate,
                )

                agg = fused_delta_aggregate(
                    jnp, gargs["delta"], dmeta, full_out, agg, op
                )
            # vertices with no in-edges hold the identity, matching the CPU
            # oracle's "no message received" semantics
            new_state, metrics = program.apply(
                state, agg, superstep_idx, memory_in, gv, jnp
            )
            self._metric_ops[(program.cache_key(), op)] = {
                k: o for k, (o, _v) in metrics.items()
            }
            return new_state, {k: v for k, (_o, v) in metrics.items()}

        return superstep

    def _superstep_fn(self, program: VertexProgram, op: str, channel: str = None):
        """Jitted single superstep (host-loop path)."""
        ch_val = program.edge_channels[channel] if channel is not None else None
        key = ("step", program.cache_key(), op, self._strategy_cfg, ch_val,
               self._delta_sig(program))
        if key not in self._compiled:
            self._compiled[key] = self.jax.jit(
                self._superstep_body(program, op, channel)
            )
        return self._compiled[key]

    def _superstep_cost(
        self, program: VertexProgram, op: str, channel, state, mem, gargs
    ) -> dict:
        """One variant's {flops, bytes_accessed, cost_source}: lower the
        superstep kernel once and harvest XLA's cost_analysis; fall back
        to the host estimator when the backend exposes none. Host-side
        only — lowering traces the body, it never dispatches or compiles."""
        from janusgraph_tpu.observability import profiler

        ch_val = program.edge_channels[channel] if channel is not None else None
        key = ("cost", program.cache_key(), op, self._strategy_cfg, ch_val,
               self._delta_sig(program))
        cost = self._kernel_costs.get(key)
        if cost is not None:
            return cost
        cost = None
        try:
            # a throwaway jit wrapper: lowering only (traces the body, no
            # compile, no dispatch) — and it must not touch _compiled,
            # which doubles as the run's retrace/compile-cache counter
            fn = self.jax.jit(self._superstep_body(program, op, channel))
            lowered = fn.lower(
                state, self.jnp.asarray(0, self.jnp.int32), mem, gargs
            )
            cost = profiler.harvest_cost(lowered)
        except Exception:  # noqa: BLE001 - cost harvest must never fail a run
            cost = None
        if cost is None:
            cost = profiler.estimate_superstep_cost(
                self.csr.num_vertices,
                self.csr.num_edges * (2 if program.undirected else 1),
                msg_cols=getattr(program, "d_pad", 1) or 1,
                weighted=self.csr.in_edge_weight is not None,
                arg_bytes=self._last_arg_bytes,
            )
        self._kernel_costs[key] = cost
        return cost

    def _fused_fn(self, program: VertexProgram, op: str):
        """A span of the BSP iteration as one compiled dispatch: a
        lax.while_loop over supersteps with `terminate_device` as the
        on-device stop condition. `steps_done0`/`limit` flow in as traced
        scalars, so the same executable serves the full run and any
        checkpoint-bounded chunk of it. No per-superstep host round trips —
        essential when the chip sits behind a high-latency PJRT link, and
        idiomatic XLA regardless (compiler-visible control flow instead of
        a host loop)."""
        key = ("fused", program.cache_key(), op, self._strategy_cfg, None,
               self._delta_sig(program))
        if key in self._compiled:
            return self._compiled[key]

        jax, jnp = self.jax, self.jnp
        body = self._superstep_body(program, op)

        def run_span(state, mem, steps_done0, limit, gargs):
            def cond(carry):
                _s, m, steps_done = carry
                # Fulgora semantics: terminate() is consulted AFTER each
                # superstep, never before the first — at steps_done == 0 the
                # aggregators are identity-seeded placeholders, and a SUM
                # convergence metric's identity (0.0) reads as "converged"
                return jnp.logical_and(
                    steps_done < limit,
                    jnp.logical_or(
                        steps_done == 0,
                        jnp.logical_not(
                            program.terminate_device(m, steps_done, jnp)
                        ),
                    ),
                )

            def loop(carry):
                s, m, steps_done = carry
                s2, m2 = body(s, steps_done, m, gargs)
                return (s2, m2, steps_done + 1)

            return jax.lax.while_loop(cond, loop, (state, mem, steps_done0))

        fn = jax.jit(run_span)
        self._compiled[key] = fn
        return fn

    # ------------------------------------------------------------------ run
    def run(
        self,
        program: VertexProgram,
        sync_every: int = 1,
        fused: bool = None,
        checkpoint_path: str = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        frontier: str = None,
        fault_hook=None,
        resume_attempts: int = 3,
    ) -> Dict[str, np.ndarray]:
        """Run to termination.

        `frontier` (default: the executor's configured mode) — per-run
        override of the frontier-compaction special case for
        ShortestPath/ConnectedComponents: "auto" sizes by graph (BFS/SSSP
        always; CC only above FRONTIER_CC_MIN_EDGES), "always" forces it,
        "off" forces the dense BSP path for this run.

        `fused` (default: auto) — compile the whole iteration into one
        dispatch (programs with a constant combiner + a terminate_device
        override). Phase-alternating programs fall back to the host loop,
        where `sync_every` controls how often the host fetches the global
        aggregators to evaluate `terminate`; between syncs everything stays
        on device and the host just enqueues work, amortizing per-step link
        latency.

        `checkpoint_path` + `checkpoint_every=N` — save (state, aggregators,
        step) every N supersteps (fused path: the while_loop is bounded into
        N-step chunks reusing ONE executable); `resume=True` continues from
        the checkpoint if present. Exceeds reference parity (SURVEY.md §5.4:
        a failed Fulgora iteration aborts outright).

        `fault_hook` (e.g. FaultPlan.olap_hook) is consulted with the
        current superstep at each host-visible boundary and may raise
        SuperstepPreempted; with checkpointing enabled the run AUTO-RESUMES
        from the last checkpoint (up to `resume_attempts` times) and the
        replay produces bitwise-identical final state — the saved arrays
        are exact, and XLA recomputes the same program over them.
        """
        jnp = self.jnp
        from janusgraph_tpu.olap.vertex_program import (
            check_weighted_transforms,
        )

        check_weighted_transforms(program, self.csr)
        # dense-feature tier plumbing: forced lane tier, the tuner's
        # feature-dim input, and the sddmm mode's support envelope
        if self._features_dim_tier and hasattr(program, "set_dim_tier"):
            if getattr(program, "dim_tier", 0) != self._features_dim_tier:
                program.set_dim_tier(self._features_dim_tier)
        self._feature_dim_run = int(getattr(program, "d_pad", 0) or 0)
        if getattr(program, "message_mode", None) == "sddmm":
            if program.undirected:
                raise ValueError(
                    "sddmm message mode aggregates over the in-CSR only — "
                    "undirected dense programs are not supported"
                )
            if type(program).channel_for is not VertexProgram.channel_for:
                raise ValueError(
                    "sddmm message mode cannot ride typed edge channels"
                )
        # computer.autotune-persist: measured records ride next to the
        # checkpoint file and calibrate the next lifetime's decide()
        self._measured_path = (
            checkpoint_path + ".autotune.json"
            if (checkpoint_path and self._autotune_persist)
            else None
        )
        if self._delta is not None:
            from janusgraph_tpu.olap.delta import (
                program_delta_compatible,
            )

            if not program_delta_compatible(program):
                raise ValueError(
                    "delta-fused runs support default-edge-view programs "
                    "only (typed edge channels aggregate over their own "
                    "packs and sddmm row-dsts are base-layout) — "
                    "materialize the overlay for this program"
                )
            # the frontier loop walks the BASE adjacency tiers; with a
            # pending overlay the dense fused path is the correct one
            frontier = "off"
        if frontier not in (None, "auto", "off", "always"):
            raise ValueError(f"unknown frontier mode: {frontier!r}")
        mode = frontier or self._frontier_cfg
        use_frontier = False
        if mode != "off" and self._frontier_family(program):
            if checkpoint_path:
                # the frontier loop has no checkpoint support; "always"
                # must never silently time the dense path under a frontier
                # label, so refuse the combination outright
                if mode == "always":
                    raise ValueError(
                        "frontier='always' cannot be combined with "
                        "checkpointing (the frontier loop does not "
                        "checkpoint) — drop checkpoint_path or use "
                        "frontier='auto'"
                    )
            elif self._frontier_eligible(program, mode):
                use_frontier = True
            elif mode == "always":
                # surface WHY the guards refused instead of silently
                # timing the dense path under a frontier label
                raise ValueError(
                    "frontier='always' but the graph exceeds the frontier "
                    f"engine's guards (|V|={self.csr.num_vertices}, "
                    f"|E|={self.csr.num_edges}; float32 label/predecessor "
                    "exactness needs |V| < 2^24, int32 expansion needs "
                    "|E| < 2^30) — use frontier='auto' or 'off'"
                )
        if fused is None:
            fused = program.fused_eligible()
        use_fused = (
            not use_frontier
            and fused
            and type(program).combiner_for is VertexProgram.combiner_for
        )
        # telemetry around the whole run: walls/sizes/compile counts are
        # all host-resident — nothing here records from traced code
        compiled_before = len(self._compiled)
        self._last_arg_bytes = 0  # a path that skips _graph_args (the
        # frontier engine ships its own tiers) must not report stale bytes
        t0 = time.perf_counter()
        with tracer.span(
            "olap.run",
            program=type(program).__name__,
            executor="tpu",
            strategy=self._strategy_cfg,
        ) as sp:
            from janusgraph_tpu.exceptions import SuperstepPreempted

            resumes = 0
            resume_steps = []
            while True:
                try:
                    if use_frontier:
                        out = self._run_frontier(program)
                    elif use_fused:
                        out = self._run_fused(
                            program, checkpoint_path, checkpoint_every,
                            resume, fault_hook,
                        )
                    else:
                        out = self._run_host_loop(
                            program, sync_every, checkpoint_path,
                            checkpoint_every, resume, fault_hook,
                        )
                    break
                except SuperstepPreempted:
                    registry.counter("olap.preemptions").inc()
                    if not (checkpoint_path and checkpoint_every) or (
                        resumes >= resume_attempts
                    ):
                        raise
                    # auto-resume: reload the last checkpoint and replay —
                    # the preempted span of supersteps is recomputed from
                    # exact saved arrays, so the final state is identical
                    resumes += 1
                    resume = True
                    resume_steps.append({
                        "attempt": resumes,
                        "at_s": round(time.perf_counter() - t0, 4),
                    })
                    registry.counter("olap.resumes").inc()
                    from janusgraph_tpu.observability import flight_recorder

                    flight_recorder.record(
                        "olap_resume", executor="tpu", attempt=resumes,
                        program=type(program).__name__,
                    )
            if self._delta is not None:
                # trim vcap-tier padding: real rows are the base snapshot
                # plus the overlay's new vertices (removed slots stay,
                # inert — repack-aligned comparisons index by vertex id)
                out = {
                    k: v[: self._delta.n_real] for k, v in out.items()
                }
                self.last_run_info["delta"] = {
                    "overlay_depth": self._delta.depth,
                    "n_extra": self._delta.n_extra,
                    "removed": int(len(self._delta.removed_idx)),
                    "fused": True,
                }
            if resumes:
                self.last_run_info["resumes"] = resumes
                self.last_run_info["resume_steps"] = resume_steps
                sp.annotate(resumes=resumes)
            self._finish_run(
                sp, program, out,
                time.perf_counter() - t0,
                len(self._compiled) - compiled_before,
            )
        return out

    # ------------------------------------------------------------ telemetry
    def _finish_run(self, sp, program, result, wall_s, new_execs) -> None:
        """Publish the finished run: enrich `last_run_info` with retrace/
        transfer/pad numbers, attach per-superstep child spans, set the
        OLAP gauges, and hand the record to the telemetry registry
        (`registry.last_run("olap")`). Everything consumed here is already
        host-resident (walls, static shapes, reduced scalars the run loop
        fetched anyway) — the compiled superstep body stays sync-free and
        graphlint JG106 keeps it that way."""
        info = self.last_run_info
        info["wall_s"] = round(wall_s, 4)
        info["retraces"] = new_execs
        info["h2d_arg_bytes"] = int(self._last_arg_bytes)
        info["d2h_bytes"] = int(
            sum(np.asarray(v).nbytes for v in result.values())
        )
        undirected = bool(getattr(program, "undirected", False))
        pad_ratio = None
        strategy_resolved = None
        hyb = self._hybrid_packs.get(undirected)
        pack = self._ell_packs.get(undirected)
        edges = self.csr.num_edges * (2 if undirected else 1)
        if hyb is not None:
            pad_ratio = round(hyb.pad_ratio, 4)
            strategy_resolved = "hybrid"
        elif pack is not None:
            slots = sum(int(b[0].size) for b in pack.buckets)
            pad_ratio = round(slots / max(1, edges), 4)
            strategy_resolved = "ell"
        # active pack's pad (legacy key name kept — every BENCH round since
        # r01 tracks it); `pad_ratio` is the strategy-neutral alias
        info["ell_pad_ratio"] = pad_ratio
        info["pad_ratio"] = pad_ratio
        if strategy_resolved is not None:
            info["strategy_resolved"] = strategy_resolved
        # the tuner's decision travels with every run record (bench +
        # /telemetry read it from here); explicit strategies still record
        # a source="config" decision for provenance
        decision = self._autotune_decisions.get(
            (undirected, self._feature_dim_run)
        )
        if decision is None and self._autotune_enabled:
            try:
                decision = self._autotune(undirected)
            except Exception:  # noqa: BLE001 - recording must not fail a run
                decision = None
        if decision is not None:
            info["autotune"] = decision.as_dict()

        records = info.get("superstep_records")
        if records is None:
            # frontier path: the tier trace IS the per-superstep record
            records = [
                {
                    "step": int(t.get("hop", i)),
                    "frontier": int(t.get("frontier", 0)),
                    "edges": int(t.get("edges", 0)),
                    "e_cap": int(t.get("E_cap", 0)),
                }
                for i, t in enumerate(info.get("tiers", []))
            ]
        n = self.g.num_vertices
        for i, r in enumerate(records):
            # dense BSP touches every vertex each superstep; the frontier
            # path records its true (compacted) sizes above
            r.setdefault("frontier", n)
            if pad_ratio is not None:
                r.setdefault("pad_ratio", pad_ratio)
            r.setdefault("h2d_bytes", info["h2d_arg_bytes"] if i == 0 else 0)
        info["superstep_records"] = records

        # roofline: every superstep record reports flops, bytes accessed,
        # operational intensity, and %-of-roofline utilization; frontier
        # records (no lowered-kernel harvest — each tier is its own
        # executable) estimate from their compacted tier sizes
        from janusgraph_tpu.observability import profiler as _profiler

        weighted = self.csr.in_edge_weight is not None
        cols = self._feature_dim_run or 1
        for r in records:
            if "flops" not in r:
                est = _profiler.estimate_superstep_cost(
                    int(r.get("frontier", n)),
                    int(r.get("edges", self.csr.num_edges)),
                    msg_cols=cols, weighted=weighted,
                )
                r.update(est)
        peaks = _profiler.device_peaks(
            getattr(self.jax.devices()[0], "device_kind", "cpu")
        )
        info["roofline_by_tier"] = _profiler.attach_roofline(
            records, _profiler.estimate_superstep_cost(
                n, self.csr.num_edges, msg_cols=cols, weighted=weighted,
                arg_bytes=info["h2d_arg_bytes"],
            ), peaks,
        )
        info["roofline"] = {
            "peak_flops": peaks["peak_flops"],
            "peak_bytes_per_s": peaks["peak_bytes_per_s"],
            "device_kind": peaks["device_kind"],
            "peaks_source": peaks["source"],
        }
        # dense tier: per-superstep MXU utilization (matmul-attributable
        # flops over the device's MXU peak) next to the VPU roofline
        if callable(getattr(program, "matmul_flops", None)):
            per_step = float(program.matmul_flops(n, edges))
            info["mxu"] = _profiler.attach_mxu(records, per_step, peaks)
            mean_util = info["mxu"].get("mean_utilization")
            if mean_util is not None:
                registry.set_gauge("olap.mxu.utilization", float(mean_util))
        if records:
            registry.set_gauge(
                "olap.roofline.operational_intensity",
                float(records[-1].get("operational_intensity") or 0.0),
            )
            util = records[-1].get("roofline_utilization")
            if util is not None:
                registry.set_gauge("olap.roofline.utilization", float(util))

        # run records and OLTP profile trees share one cost vocabulary:
        # the `resources` block, accrued into the ambient ledger too (an
        # olap.run inside a profiled request bills its transfer bytes)
        info["resources"] = {
            "h2d_bytes": info["h2d_arg_bytes"],
            "d2h_bytes": info["d2h_bytes"],
            "flops": sum(r.get("flops", 0.0) for r in records),
            "bytes_accessed": sum(
                r.get("bytes_accessed", 0.0) for r in records
            ),
        }
        _profiler.accrue(
            h2d_bytes=info["h2d_arg_bytes"], d2h_bytes=info["d2h_bytes"]
        )
        _profiler.accrue_wall("olap", wall_s * 1000.0)

        # compile-cache economics per run: `new_execs` superstep dispatches
        # paid a compile (misses), the rest reused an executable (hits) —
        # the retrace-vs-reuse split the padding/tier design exists to win
        dispatches = max(len(records), 1)
        misses = min(new_execs, dispatches)
        info["compile_cache"] = {
            "hits": dispatches - misses,
            "misses": misses,
            "compiled_total": len(self._compiled),
        }
        registry.counter("olap.compile_cache.hits").inc(dispatches - misses)
        registry.counter("olap.compile_cache.misses").inc(misses)

        # device-memory gauges: real allocator stats where the backend
        # exposes them, host-resident estimate otherwise (CPU/interpret)
        info["device_memory"] = self._device_memory(info)
        registry.set_gauge(
            "olap.device.bytes_in_use",
            float(info["device_memory"]["bytes_in_use"]),
        )
        if "peak_bytes_in_use" in info["device_memory"]:
            registry.set_gauge(
                "olap.device.peak_bytes_in_use",
                float(info["device_memory"]["peak_bytes_in_use"]),
            )

        slowest = None
        for r in records[:128]:
            s = tracer.record_span(
                "superstep", float(r.get("wall_ms", 0.0)),
                **{k: v for k, v in r.items() if k != "wall_ms"},
            )
            if slowest is None or s.duration_ms > slowest.duration_ms:
                slowest = s
        if slowest is not None:
            # exemplar: the run record points at the slowest superstep's
            # span so a dashboard number links to the concrete span tree
            info["slowest_superstep"] = {
                "step": slowest.attrs.get("step"),
                "wall_ms": round(slowest.duration_ms, 4),
                "span_id": f"{slowest.span_id:016x}",
                "trace_id": f"{slowest.trace_id:016x}",
            }
        sp.annotate(
            path=info.get("path"),
            supersteps=info.get("supersteps"),
            wall_s=info["wall_s"],
            retraces=new_execs,
            ell_pad_ratio=pad_ratio,
            h2d_arg_bytes=info["h2d_arg_bytes"],
            d2h_bytes=info["d2h_bytes"],
        )

        registry.counter("olap.runs").inc()
        registry.timer("olap.run").update(int(wall_s * 1e9))
        registry.set_gauge(
            "olap.superstep.count", float(info.get("supersteps", 0) or 0)
        )
        registry.set_gauge("olap.run.wall_ms", round(wall_s * 1000.0, 3))
        registry.set_gauge(
            "olap.transfer.h2d_bytes", float(info["h2d_arg_bytes"])
        )
        registry.set_gauge("olap.transfer.d2h_bytes", float(info["d2h_bytes"]))
        if pad_ratio is not None:
            registry.set_gauge("olap.ell.pad_ratio", pad_ratio)
        if records:
            registry.set_gauge(
                "olap.frontier.last", float(records[-1].get("frontier", n))
            )
            registry.histogram("olap.frontier.size").observe(
                float(records[-1].get("frontier", n))
            )
        # computer.autotune-persist: the record the next executor lifetime
        # feeds back into decide() as its `measured` calibration input
        if self._measured_path and records and pad_ratio is not None:
            from janusgraph_tpu.olap import autotune as _at

            walls = sorted(float(r.get("wall_ms", 0.0)) for r in records)
            # single-device lifetime: the shard_count=1 slot (a multi-chip
            # run records under its own mesh size — the layouts must not
            # clobber each other's calibration)
            _at.save_measured(self._measured_path, {
                "strategy": strategy_resolved,
                "pad_ratio": pad_ratio,
                "superstep_ms": walls[len(walls) // 2],
                "roofline_by_tier": info.get("roofline_by_tier"),
            }, shard_count=1)
        registry.record_run("olap", info)

    def _device_memory(self, info) -> dict:
        """Device-memory occupancy for the run record: real allocator
        stats where the backend exposes them (``Device.memory_stats`` on
        TPU/GPU), else a host-resident static-shape estimate (CPU and
        interpret mode report no allocator). Host-side only — asking the
        allocator is not a device sync."""
        stats = None
        try:
            stats = self.jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 - backend-dependent API
            stats = None
        if stats and "bytes_in_use" in stats:
            out = {
                "source": "device",
                "bytes_in_use": int(stats["bytes_in_use"]),
            }
            if "peak_bytes_in_use" in stats:
                out["peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
            if "bytes_limit" in stats:
                out["bytes_limit"] = int(stats["bytes_limit"])
            return out
        return {
            "source": "host-estimate",
            "bytes_in_use": int(info.get("h2d_arg_bytes", 0))
            + int(info.get("d2h_bytes", 0)),
        }

    #: graphs below this edge count run CC through the fused dense path
    #: under frontier="auto": the frontier loop pays ~2 host round trips
    #: per superstep, which only amortizes once a dense superstep costs
    #: more than dispatch (BFS keeps frontier at every size — its dense
    #: path rescans |E| for hops that touch a handful of vertices)
    FRONTIER_CC_MIN_EDGES = 1 << 20

    @staticmethod
    def _frontier_family(program: VertexProgram) -> bool:
        from janusgraph_tpu.olap.programs.connected_components import (
            ConnectedComponentsProgram,
        )
        from janusgraph_tpu.olap.programs.shortest_path import (
            ShortestPathProgram,
        )

        return type(program) in (
            ShortestPathProgram, ConnectedComponentsProgram
        )

    def _frontier_eligible(self, program: VertexProgram, mode: str) -> bool:
        from janusgraph_tpu.olap.frontier import FrontierEngine
        from janusgraph_tpu.olap.programs.connected_components import (
            ConnectedComponentsProgram,
        )
        from janusgraph_tpu.olap.programs.shortest_path import (
            ShortestPathProgram,
        )

        if not self._frontier_family(program):
            return False
        if self.csr.num_edges >= FrontierEngine.MAX_EDGES:
            return False
        if type(program) is ShortestPathProgram:
            # track_paths encodes predecessor indices in float32 — the
            # dense path's setup() raises above 2^24 vertices; mirror that
            # guard here instead of silently rounding predecessors
            return not (
                program.track_paths
                and self.csr.num_vertices >= (1 << 24)
            )
        if type(program) is ConnectedComponentsProgram:
            # labels are float32 vertex indices: exact below 2^24 only
            return self.csr.num_vertices < (1 << 24) and (
                mode == "always"
                or self.csr.num_edges >= self.FRONTIER_CC_MIN_EDGES
            )
        return False

    def _run_frontier(self, program: VertexProgram) -> Dict[str, np.ndarray]:
        import time

        from janusgraph_tpu.olap.frontier import FrontierEngine
        from janusgraph_tpu.olap.programs.connected_components import (
            ConnectedComponentsProgram,
        )

        if self._frontier_engine is None:
            if self._autotune_enabled:
                # the tier-schedule half of the decision: computed before
                # the engine snapshots it (aggregation half unused here)
                self._autotune(False)
            self._frontier_engine = FrontierEngine(self)
        t0 = time.perf_counter()
        if type(program) is ConnectedComponentsProgram:
            out = self._frontier_engine.run_cc(program)
        else:
            out = self._frontier_engine.run(program)
        trace = getattr(self._frontier_engine, "last_trace", [])
        self.last_run_info = {
            "path": "frontier",
            "supersteps": len(trace),
            "wall_s": round(time.perf_counter() - t0, 4),
            "tiers": trace,
        }
        return out

    def _run_fused(
        self,
        program: VertexProgram,
        checkpoint_path: str,
        checkpoint_every: int,
        resume: bool,
        fault_hook=None,
    ) -> Dict[str, np.ndarray]:
        jnp = self.jnp
        op = program.combiner
        max_iter = program.max_iterations
        steps_done = 0
        state = mem = None

        if resume and checkpoint_path:
            from janusgraph_tpu.olap.checkpoint import load_checkpoint

            ck = load_checkpoint(checkpoint_path)
            if ck is not None:
                state, mem, steps_done = ck
                state = {k: jnp.asarray(v) for k, v in state.items()}
                mem = {k: jnp.asarray(v, jnp.float32) for k, v in mem.items()}

        if state is None:
            state, init_metrics = program.setup(self.g, jnp)
            state = {k: jnp.asarray(v) for k, v in state.items()}
            mem0 = {
                k: jnp.asarray(v, dtype=jnp.float32)
                for k, (_o, v) in init_metrics.items()
            }
            if max_iter == 0:
                self.last_run_info = {"path": "fused", "supersteps": 0}
                return {k: np.asarray(v) for k, v in state.items()}
            # The while_loop carry must use apply's aggregator pytree, which
            # can add keys over setup's. Learn it via an abstract trace (no
            # XLA compile — the trace records each metric's monoid op as a
            # side effect), then seed missing keys with the monoid identity
            # so superstep 0 runs INSIDE the fused executable. One compile
            # per program instead of two (the separate superstep-0
            # executable doubled the dominant bucket-aggregate compile:
            # measured 123s -> ~60s for s20 PageRank).
            mkey = (program.cache_key(), op)
            if mkey not in self._metric_ops:
                # the view-usage discovery trace records metric ops too;
                # reuse this run's state/mem so discovery is abstract-only
                self._used_view_keys(program, op, state=state, mem0=mem0)
            mops = self._metric_ops[mkey]
            mem = {
                k: (
                    mem0[k]
                    if k in mem0
                    else jnp.asarray(Combiner.IDENTITY[mops[k]], jnp.float32)
                )
                for k in mops
            }
            steps_done = 0

        fused_key = ("fused", program.cache_key(), op, self._strategy_cfg,
                     None, self._delta_sig(program))
        cold = fused_key not in self._compiled
        fn = self._fused_fn(program, op)
        gargs = self._graph_args(program, op)
        # per-superstep cost from the SINGLE-step kernel's lowering (the
        # fused while_loop executable's analysis would mix in the loop
        # plumbing; the step body is the dispatch-equivalent unit)
        cost = self._superstep_cost(program, op, None, state, mem, gargs)
        records = []
        first_dispatch_s = None
        while steps_done < max_iter:
            if fault_hook is not None:
                # the fused executable is opaque between chunk boundaries:
                # preemption lands at the superstep granularity the
                # checkpoint cadence exposes
                fault_hook(steps_done)
            limit = max_iter
            if checkpoint_every:
                limit = min(steps_done + checkpoint_every, max_iter)
            c0 = time.perf_counter()
            state, mem, steps_dev = fn(
                state,
                mem,
                jnp.asarray(steps_done, jnp.int32),
                jnp.asarray(limit, jnp.int32),
                gargs,
            )
            new_steps = int(steps_dev)  # the per-chunk host sync (existing)
            chunk_s = time.perf_counter() - c0
            if first_dispatch_s is None:
                first_dispatch_s = chunk_s
            # one executable covers the whole chunk: per-superstep wall is
            # the amortized share (flagged approx=True); the first chunk of
            # a cold executable carries the compile
            ran = max(1, new_steps - steps_done)
            per_ms = round(chunk_s * 1000.0 / ran, 3)
            for s in range(steps_done, max(new_steps, steps_done)):
                records.append({
                    "step": s,
                    "wall_ms": per_ms,
                    "approx": True,
                    "compiled": cold and not records,
                    **cost,
                })
            terminated = new_steps < limit or new_steps == steps_done
            steps_done = max(new_steps, steps_done)
            if checkpoint_path and checkpoint_every:
                from janusgraph_tpu.olap.checkpoint import save_checkpoint

                ck0 = time.perf_counter()
                save_checkpoint(
                    checkpoint_path,
                    {k: np.asarray(v) for k, v in state.items()},
                    {k: np.asarray(v) for k, v in mem.items()},
                    steps_done,
                )
                if records:
                    # timeline marker: the save's wall, stamped on the
                    # superstep that paid it (observability/timeline.py)
                    records[-1]["checkpoint_ms"] = round(
                        (time.perf_counter() - ck0) * 1000.0, 3
                    )
            if terminated:
                break
        self.last_run_info = {
            "path": "fused",
            "supersteps": steps_done,
            "superstep_records": records,
            # compile rides the first dispatch of a cold executable; the
            # split is only separable when later dispatches exist
            "first_dispatch_s": round(first_dispatch_s or 0.0, 4),
            "compile_in_first_dispatch": cold,
        }
        return {k: np.asarray(v) for k, v in state.items()}

    def _run_host_loop(
        self,
        program: VertexProgram,
        sync_every: int = 1,
        checkpoint_path: str = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        fault_hook=None,
    ) -> Dict[str, np.ndarray]:
        jnp = self.jnp
        memory = Memory()
        state = None
        start_step = 0
        if resume and checkpoint_path:
            from janusgraph_tpu.olap.checkpoint import load_checkpoint

            ck = load_checkpoint(checkpoint_path)
            if ck is not None:
                ck_state, ck_mem, start_step = ck
                state = {k: jnp.asarray(v) for k, v in ck_state.items()}
                memory.values = {k: float(v) for k, v in ck_mem.items()}
                memory.superstep = start_step
        if state is None:
            state, init_metrics = program.setup(self.g, jnp)
            memory.reduce_in(init_metrics)
            memory.superstep = 0

        # device-resident aggregators: no H2D after this point
        device_memory = {
            k: jnp.asarray(v, dtype=jnp.float32) for k, v in memory.values.items()
        }
        steps_done = start_step
        records = []
        for step in range(start_step, program.max_iterations):
            if fault_hook is not None:
                fault_hook(step)
            op = program.combiner_for(step)
            ch = program.channel_for(step)
            s0 = time.perf_counter()
            compiled_before = len(self._compiled)
            # seed view-usage discovery with this run's live pytrees so the
            # cache-miss path never re-runs program.setup
            self._used_view_keys(
                program, op, ch, state=state, mem0=device_memory
            )
            fn = self._superstep_fn(program, op, ch)
            gargs = self._graph_args(program, op, ch)
            # lower-once cost harvest (memoized per compiled variant):
            # flops + bytes accessed feed the per-superstep roofline
            cost = self._superstep_cost(
                program, op, ch, state, device_memory, gargs
            )
            state, metrics = fn(
                state,
                jnp.asarray(step, dtype=jnp.int32),
                device_memory,
                gargs,
            )
            device_memory = {
                k: metrics.get(k, device_memory.get(k)) for k in
                set(device_memory) | set(metrics)
            }
            # host-side dispatch wall (async enqueue unless the cadence
            # below syncs) + whether this step built a fresh executable —
            # the compile-vs-execute split at superstep granularity
            records.append({
                "step": step,
                "wall_ms": round((time.perf_counter() - s0) * 1000.0, 3),
                "combiner": op,
                "channel": ch,
                "compiled": len(self._compiled) > compiled_before,
                **cost,
            })
            steps_done += 1
            last = step == program.max_iterations - 1
            if steps_done % sync_every == 0 or last:
                host_vals = self.jax.device_get(metrics)  # one round trip
                memory.values = {k: float(v) for k, v in host_vals.items()}
                memory.superstep = steps_done
                if checkpoint_path and checkpoint_every and (
                    steps_done % checkpoint_every == 0 or last
                ):
                    from janusgraph_tpu.olap.checkpoint import save_checkpoint

                    ck0 = time.perf_counter()
                    save_checkpoint(
                        checkpoint_path,
                        {k: np.asarray(v) for k, v in state.items()},
                        memory.values,
                        steps_done,
                    )
                    # timeline marker (observability/timeline.py)
                    records[-1]["checkpoint_ms"] = round(
                        (time.perf_counter() - ck0) * 1000.0, 3
                    )
                if program.terminate(memory):
                    break
        self.last_run_info = {
            "path": "host-loop",
            "supersteps": steps_done,
            "superstep_records": records,
        }
        return {k: np.asarray(v) for k, v in state.items()}

    # ------------------------------------------------------------ write-back
    def write_back(self, graph, result: Dict[str, np.ndarray], keys=None) -> None:
        """Persist compute-key arrays as vertex properties in batched txs
        (reference: FulgoraGraphComputer.java:359-437 VertexPropertyWriter)."""
        write_back(graph, self.csr, result, keys)


def write_back(graph, csr: CSRGraph, result: Dict[str, np.ndarray], keys=None, batch: int = 10_000) -> None:
    """Persist compute-key arrays as vertex properties.

    Columnar fast path (reference contrast: FulgoraGraphComputer.java:359-437
    runs full OLTP transactions per vertex; here unindexed SINGLE-cardinality
    float keys are encoded as raw property cells — one struct.pack per
    vertex, batched mutate_many per chunk, bulk relation-id spans — which is
    the batch-loading semantics the reference reserves for its bulk mode).
    Indexed or non-SINGLE keys fall back to the transactional path so index
    maintenance stays correct.
    """
    from janusgraph_tpu.core.codecs import Cardinality

    mgmt = graph.management()
    names = list(result.keys() if keys is None else keys)
    for name in names:
        if graph.schema_cache.get_by_name(name) is None:
            mgmt.make_property_key(name, float)
    vids = csr.vertex_ids
    for name in names:
        pk = graph.schema_cache.get_by_name(name)
        indexed = any(
            pk.id in idx.key_ids for idx in graph.indexes.values()
        )
        if indexed or pk.cardinality != Cardinality.SINGLE or pk.data_type is not float:
            # tx path: index maintenance + schema type checks stay enforced
            _write_back_tx(graph, vids, name, result[name], batch)
            continue
        _write_back_columnar(graph, vids, pk, result[name], batch)


def _write_back_tx(graph, vids, name, values, batch: int) -> None:
    values = np.asarray(values, dtype=np.float64)
    for lo in range(0, len(vids), batch):
        tx = graph.new_transaction(read_only=False)  # write-back writes
        for i in range(lo, min(lo + batch, len(vids))):
            v = tx.get_vertex(int(vids[i]))
            if v is not None:
                v.property(name, float(values[i]))
        tx.commit()


def _write_back_columnar(graph, vids, pk, values, batch: int) -> None:
    import struct

    if len(vids) == 0:
        return
    values = np.asarray(values, dtype=np.float64)
    es = graph.edge_serializer
    idm = graph.idm
    n = len(vids)
    spans = graph.id_assigner.assign_relation_ids(n)
    rel_ids = np.concatenate(
        [np.arange(s, s + ln, dtype=np.int64) for s, ln in spans]
    )
    # DERIVE the cell layout from the codec instead of duplicating its
    # knowledge: render two probe cells and split them around the varying
    # fields. The vectorized fill below then only substitutes the rel-id
    # and float payload inside the codec's own byte layout — if the cell
    # format evolves, the probe check fails loudly instead of this path
    # silently writing a stale format (VERDICT r3 weak #8).
    probe_rel, probe_val = 1, 0.0
    col, probe_cell = es.write_property(pk.id, probe_rel, probe_val)
    expect = (
        struct.pack(">Q", probe_rel)
        + struct.pack(">H", graph.serializer.serializer_for(0.0).type_id)
        + struct.pack(">d", probe_val)
    )
    if probe_cell != expect:
        # codec layout changed: fall back to rendering through the codec
        # per value (slower, always correct)
        keys = idm.get_keys_array(vids)
        for lo in range(0, n, batch):
            btx = graph.backend.begin_transaction()
            for i in range(lo, min(lo + batch, n)):
                c, v = es.write_property(
                    pk.id, int(rel_ids[i]), float(values[i])
                )
                btx.mutate_edges(keys[i], [(c, v)], [])
            btx.commit()
        return
    mid = struct.pack(">H", graph.serializer.serializer_for(0.0).type_id)
    keys = idm.get_keys_array(vids)
    rel_raw = rel_ids.astype(">u8").tobytes()
    val_raw = values.astype(">f8").tobytes()
    for lo in range(0, n, batch):
        hi = min(lo + batch, n)
        btx = graph.backend.begin_transaction()
        for i in range(lo, hi):
            val = (
                rel_raw[8 * i : 8 * i + 8]
                + mid
                + val_raw[8 * i : 8 * i + 8]
            )
            btx.mutate_edges(keys[i], [(col, val)], [])
        btx.commit()
