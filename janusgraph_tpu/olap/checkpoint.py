"""Superstep checkpointing for BSP runs.

The reference has NO OLAP checkpointing — a failed Fulgora iteration aborts
(reference: FulgoraGraphComputer.java:269-277; SURVEY.md §5.4 notes superstep
checkpointing "should exceed parity"). Here a checkpoint is the dense vertex
state dict + reduced aggregators + step counter, written atomically as .npz;
executors save every `checkpoint_every` supersteps and resume mid-iteration.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np

_STATE = "state__"
_MEM = "mem__"
_META = "meta__steps"


def save_checkpoint(
    path: str,
    state: Dict[str, np.ndarray],
    memory: Dict[str, np.ndarray],
    steps_done: int,
) -> None:
    """Atomic write: tmp file in the same directory, then rename."""
    arrays = {_STATE + k: np.asarray(v) for k, v in state.items()}
    arrays.update({_MEM + k: np.asarray(v) for k, v in memory.items()})
    arrays[_META] = np.asarray(steps_done, dtype=np.int64)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(
    path: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], int]]:
    """Returns (state, memory, steps_done) or None if absent."""
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        state = {
            k[len(_STATE):]: z[k] for k in z.files if k.startswith(_STATE)
        }
        memory = {
            k[len(_MEM):]: z[k] for k in z.files if k.startswith(_MEM)
        }
        steps = int(z[_META])
    return state, memory, steps
