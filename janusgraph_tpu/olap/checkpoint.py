"""Superstep checkpointing for BSP runs.

The reference has NO OLAP checkpointing — a failed Fulgora iteration aborts
(reference: FulgoraGraphComputer.java:269-277; SURVEY.md §5.4 notes superstep
checkpointing "should exceed parity"). Here a checkpoint is the dense vertex
state dict + reduced aggregators + step counter, written atomically as .npz;
executors save every `checkpoint_every` supersteps and resume mid-iteration
(automatically on SuperstepPreempted — the chaos engine's preemption fault).

Durability against torn writes: every checkpoint embeds a content digest
over its arrays, and each save demotes the previous checkpoint to
``<path>.prev`` before promoting the new one. ``load_checkpoint`` verifies
the digest and falls back to ``.prev`` when the newest file is truncated or
corrupted — a crash mid-save (or a byte flipped on disk) costs one
checkpoint interval, never the run.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np

_STATE = "state__"
_MEM = "mem__"
_META = "meta__steps"
_DIGEST = "meta__digest"


def _content_digest(arrays: Dict[str, np.ndarray]) -> np.ndarray:
    """Digest over names, dtypes, shapes, and raw bytes of every payload
    array (sorted by name, so dict order never matters)."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == _DIGEST:
            continue
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return np.frombuffer(h.digest(), dtype=np.uint8).copy()


def save_checkpoint(
    path: str,
    state: Dict[str, np.ndarray],
    memory: Dict[str, np.ndarray],
    steps_done: int,
) -> None:
    """Atomic write: tmp file in the same directory, then rename. The
    previous checkpoint survives as ``<path>.prev``."""
    arrays = {_STATE + k: np.asarray(v) for k, v in state.items()}
    arrays.update({_MEM + k: np.asarray(v) for k, v in memory.items()})
    arrays[_META] = np.asarray(steps_done, dtype=np.int64)
    arrays[_DIGEST] = _content_digest(arrays)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        if os.path.exists(path):
            # demote the old checkpoint BEFORE promoting the new one: a
            # crash between the two renames leaves .prev as the newest
            # intact checkpoint, which load_checkpoint falls back to
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.record("checkpoint", action="save", steps=steps_done)


def _load_verified(
    path: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], int]]:
    """Load one file, verifying the embedded digest. Returns None when the
    file is missing, truncated, unreadable, or fails verification."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception:  # zipfile/format errors: a torn or truncated write
        return None
    if _META not in arrays:
        return None
    stored = arrays.pop(_DIGEST, None)
    if stored is None or not np.array_equal(
        stored, _content_digest(arrays)
    ):
        return None  # bytes changed since save: corrupted
    state = {
        k[len(_STATE):]: v for k, v in arrays.items() if k.startswith(_STATE)
    }
    memory = {
        k[len(_MEM):]: v for k, v in arrays.items() if k.startswith(_MEM)
    }
    return state, memory, int(arrays[_META])


def load_checkpoint(
    path: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], int]]:
    """Returns (state, memory, steps_done), falling back to ``<path>.prev``
    when the newest checkpoint is torn/corrupted; None when neither file
    holds a verifiable checkpoint."""
    loaded = _load_verified(path)
    if loaded is not None:
        return loaded
    fallback = _load_verified(path + ".prev")
    if fallback is not None and os.path.exists(path):
        from janusgraph_tpu.observability import flight_recorder, registry

        registry.counter("olap.checkpoint.fallback").inc()
        # the newest checkpoint was torn/corrupt and .prev saved the run —
        # exactly the kind of event a post-mortem needs on the timeline
        flight_recorder.record(
            "checkpoint", action="fallback", steps=int(fallback[2]),
        )
    return fallback
