from janusgraph_tpu.olap.computer import ComputerResult, GraphComputer, run_on  # noqa: F401
from janusgraph_tpu.olap.csr import CSRGraph, csr_from_edges, load_csr  # noqa: F401
from janusgraph_tpu.olap.vertex_program import (  # noqa: F401
    Combiner,
    EdgeTransform,
    Memory,
    VertexProgram,
)
from janusgraph_tpu.olap.mapreduce import (  # noqa: F401
    ClusterCountMapReduce,
    MapReduce,
    StatsMapReduce,
    TopKMapReduce,
    run_map_reduce,
)
from janusgraph_tpu.olap.checkpoint import (  # noqa: F401
    load_checkpoint,
    save_checkpoint,
)
from janusgraph_tpu.olap.features import (  # noqa: F401
    DenseVertexProgram,
    MessageMode,
)
from janusgraph_tpu.olap.spillover import (  # noqa: F401
    SpilloverPlan,
    SpilloverPlanner,
    promoted_digests,
)
