from janusgraph_tpu.olap.computer import ComputerResult, GraphComputer, run_on  # noqa: F401
from janusgraph_tpu.olap.csr import CSRGraph, csr_from_edges, load_csr  # noqa: F401
from janusgraph_tpu.olap.vertex_program import (  # noqa: F401
    Combiner,
    EdgeTransform,
    Memory,
    VertexProgram,
)
