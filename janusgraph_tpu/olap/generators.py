"""Synthetic graph generators for benchmarks and scale tests.

R-MAT / Kronecker generator with graph500 reference parameters
(a,b,c,d = 0.57, 0.19, 0.19, 0.05, edge factor 16) — the workload family
behind BASELINE configs #3 and the north-star metric. Fully vectorized:
one random draw per (edge, level).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 1,
    permute: bool = True,
) -> Tuple[int, np.ndarray, np.ndarray]:
    """Return (n, src, dst) with n = 2**scale, m = n * edge_factor edges."""
    n = 1 << scale
    m = n * edge_factor
    rng = np.random.default_rng(seed)

    from janusgraph_tpu import native

    nat = native.rmat_edges(scale, m, seed, a, b, c)
    if nat is not None:
        src32, dst32 = nat
        if permute:
            perm = rng.permutation(n).astype(np.int32)
            src32 = perm[src32]
            dst32 = perm[dst32]
        return n, src32, dst32
    src = np.zeros(m, dtype=np.int64)
    dst = np.zeros(m, dtype=np.int64)
    ab = a + b
    abc = a + b + c
    for _ in range(scale):
        r = rng.random(m)
        src_bit = r >= ab
        dst_bit = ((r >= a) & (r < ab)) | (r >= abc)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    if permute:
        perm = rng.permutation(n)
        src = perm[src]
        dst = perm[dst]
    return n, src.astype(np.int32), dst.astype(np.int32)


def rmat_csr(scale: int, edge_factor: int = 16, seed: int = 1, weights: bool = False):
    from janusgraph_tpu.olap.csr import csr_from_edges

    n, src, dst = rmat_edges(scale, edge_factor, seed=seed)
    w = None
    if weights:
        w = np.random.default_rng(seed + 1).uniform(0.5, 2.0, len(src)).astype(
            np.float32
        )
    return csr_from_edges(n, src, dst, w)
