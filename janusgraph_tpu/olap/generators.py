"""Synthetic graph generators for benchmarks and scale tests.

R-MAT / Kronecker generator with graph500 reference parameters
(a,b,c,d = 0.57, 0.19, 0.19, 0.05, edge factor 16) — the workload family
behind BASELINE configs #3 and the north-star metric. Fully vectorized:
one random draw per (edge, level).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 1,
    permute: bool = True,
) -> Tuple[int, np.ndarray, np.ndarray]:
    """Return (n, src, dst) with n = 2**scale, m = n * edge_factor edges."""
    n = 1 << scale
    m = n * edge_factor
    rng = np.random.default_rng(seed)

    from janusgraph_tpu import native

    nat = native.rmat_edges(scale, m, seed, a, b, c)
    if nat is not None:
        src32, dst32 = nat
        if permute:
            perm = rng.permutation(n).astype(np.int32)
            src32 = perm[src32]
            dst32 = perm[dst32]
        return n, src32, dst32
    src = np.zeros(m, dtype=np.int64)
    dst = np.zeros(m, dtype=np.int64)
    ab = a + b
    abc = a + b + c
    for _ in range(scale):
        r = rng.random(m)
        src_bit = r >= ab
        dst_bit = ((r >= a) & (r < ab)) | (r >= abc)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    if permute:
        perm = rng.permutation(n)
        src = perm[src]
        dst = perm[dst]
    return n, src.astype(np.int32), dst.astype(np.int32)


def rmat_csr(scale: int, edge_factor: int = 16, seed: int = 1, weights: bool = False):
    from janusgraph_tpu.olap.csr import csr_from_edges

    n, src, dst = rmat_edges(scale, edge_factor, seed=seed)
    w = None
    if weights:
        w = np.random.default_rng(seed + 1).uniform(0.5, 2.0, len(src)).astype(
            np.float32
        )
    return csr_from_edges(n, src, dst, w)


def _land_edge_count(deg: np.ndarray, target: int, rng) -> np.ndarray:
    """Nudge a per-vertex degree vector until it sums EXACTLY to `target`
    (dataset-sized proxies must hit documented edge counts). np.add.at /
    np.subtract.at — plain fancy-index += silently drops duplicate
    indices. The clamp-to-1 after trimming can re-add mass, so iterate;
    unreachable targets (< len(deg) with the min-1 floor) stop early."""
    n = len(deg)
    for _ in range(8):
        diff = target - int(deg.sum())
        if diff == 0:
            break
        if diff > 0:
            np.add.at(deg, rng.integers(0, n, diff), 1)
        else:
            np.subtract.at(deg, rng.integers(0, n, -diff), 1)
            np.maximum(deg, 1, out=deg)
            if int(deg.sum()) <= n:
                break
    return deg


def ldbc_snb_edges(
    scale: int,
    edge_factor: int = 18,
    intra_community: float = 0.8,
    seed: int = 7,
) -> Tuple[int, np.ndarray, np.ndarray, dict]:
    """Deterministic LDBC-SNB-shaped social network proxy at 2**scale
    vertices (see _snb_edges_n for the shape model)."""
    return _snb_edges_n(1 << scale, edge_factor, intra_community, seed)


def _snb_edges_n(
    n: int,
    edge_factor: float = 18,
    intra_community: float = 0.8,
    seed: int = 7,
) -> Tuple[int, np.ndarray, np.ndarray, dict]:
    """Deterministic LDBC-SNB-shaped social network proxy
    (BASELINE configs #2/#5 name LDBC SF1/SF10 datasets; no generator or
    dataset ships in this environment, so this reproduces the *shape* the
    SNB person-knows-person network is documented to have: lognormal-ish
    heavy-tailed degrees, strong community locality with a minority of
    cross-community edges, and community-correlated attributes).

    Returns (n, src, dst, properties) with properties:
      community    (n,) int32 — community id (city/university analogue)
      country      (n,) int32 — coarser grouping correlated with community
      creation_day (n,) int32 — days-since-epoch-style attribute

    Fully vectorized; same seed -> identical graph.
    """
    rng = np.random.default_rng(seed)

    # community sizes ~ Zipf: heavy-tailed like SNB city populations
    n_comm = max(8, n >> 7)
    raw = 1.0 / np.arange(1, n_comm + 1, dtype=np.float64) ** 0.85
    comm_of = rng.choice(n_comm, size=n, p=raw / raw.sum()).astype(np.int32)

    # per-vertex out-degree: lognormal, clipped, scaled to the edge factor
    deg = rng.lognormal(mean=0.0, sigma=1.1, size=n)
    deg = np.maximum(1, (deg * (edge_factor / deg.mean()))).astype(np.int64)
    deg = np.minimum(deg, n // 4)
    deg = _land_edge_count(deg, int(round(n * edge_factor)), rng)
    m = int(deg.sum())
    src = np.repeat(np.arange(n, dtype=np.int64), deg)

    # community membership table for intra-community endpoint sampling
    order = np.argsort(comm_of, kind="stable")
    sizes = np.bincount(comm_of, minlength=n_comm).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(sizes)[:-1]])

    u = rng.random(m)
    intra = rng.random(m) < intra_community
    c_src = comm_of[src]
    # intra: uniform member of the source's community
    pick = starts[c_src] + np.minimum(
        (u * np.maximum(sizes[c_src], 1)).astype(np.int64),
        np.maximum(sizes[c_src] - 1, 0),
    )
    dst_intra = order[pick]
    # inter: degree-weighted global endpoint (preferential attachment-ish,
    # reproducing SNB's hub overlap across communities)
    cum = np.cumsum(deg)
    dst_inter = np.searchsorted(cum, rng.random(m) * cum[-1], side="right")
    dst = np.where(intra, dst_intra, dst_inter).astype(np.int64)
    # drop self-loops by nudging to the next vertex
    self_loop = dst == src
    dst[self_loop] = (dst[self_loop] + 1) % n

    props = {
        "community": comm_of,
        "country": (comm_of % 60).astype(np.int32),
        "creation_day": rng.integers(0, 3650, n).astype(np.int32),
    }
    return n, src.astype(np.int32), dst.astype(np.int32), props


def ldbc_snb_csr(scale: int, edge_factor: int = 18, seed: int = 7):
    """CSR form of the LDBC-SNB-shaped proxy with properties attached."""
    from janusgraph_tpu.olap.csr import csr_from_edges

    n, src, dst, props = ldbc_snb_edges(scale, edge_factor, seed=seed)
    csr = csr_from_edges(n, src, dst)
    csr.properties.update(props)
    return csr


#: published LDBC-SNB scale-factor sizes (all entity types; BASELINE.json
#: rows 2/5 cite SF1 and SF10): sf -> (vertices, total edges)
LDBC_SF_SIZES = {1: (3_200_000, 17_300_000), 10: (30_000_000, 176_000_000)}


def ldbc_sf_csr(sf: int = 1, seed: int = 7, scale_down: int = 1):
    """SF-sized SNB-shaped proxy (VERDICT r4 #6): the documented SF1 size
    (~3.2M vertices, ~17.3M edges) with the _snb_edges_n community/degree
    shape. `scale_down` divides both dimensions for CPU-affordable rungs
    (the shape — community structure, degree tail, intra ratio — is
    size-invariant)."""
    from janusgraph_tpu.olap.csr import csr_from_edges

    nv, ne = LDBC_SF_SIZES[sf]
    nv //= scale_down
    ne //= scale_down
    n, src, dst, props = _snb_edges_n(nv, ne / nv, seed=seed)
    csr = csr_from_edges(n, src, dst)
    csr.properties.update(props)
    return csr


def twitter_edges(
    n: int,
    edge_factor: float = 35.0,
    alpha: float = 2.3,
    seed: int = 11,
) -> Tuple[int, np.ndarray, np.ndarray]:
    """Twitter-2010-shaped follower-graph proxy (BASELINE config #4 names
    the Twitter-2010 crawl: 41.6M users, 1.47B follows, in-degree power
    law with exponent ~2.3 and celebrity hubs followed by a few percent of
    ALL users). The dataset itself doesn't ship here; this reproduces the
    documented shape at any size:

      - in-degree ∝ Pareto(alpha-1) attachment weights → power-law
        in-degrees with exponent ~alpha and extreme hubs,
      - out-degrees lognormal-heavy (active users follow thousands),
      - no community structure (unlike the SNB proxy) — follower graphs
        are hub-dominated, which is exactly what stresses PeerPressure's
        supernode row-split path.

    Fully vectorized; same seed -> identical graph.
    """
    rng = np.random.default_rng(seed)
    m = int(n * edge_factor)
    out_deg = rng.lognormal(mean=0.0, sigma=1.6, size=n)
    out_deg = np.maximum(1, out_deg * (edge_factor / out_deg.mean()))
    out_deg = np.minimum(out_deg.astype(np.int64), n // 2)
    out_deg = _land_edge_count(out_deg, m, rng)
    m = int(out_deg.sum())
    src = np.repeat(np.arange(n, dtype=np.int64), out_deg)

    # attachment weights: Pareto tail → celebrity in-degree hubs
    w = (1.0 / rng.random(n)) ** (1.0 / (alpha - 1.0))
    cum = np.cumsum(w)
    dst = np.searchsorted(cum, rng.random(m) * cum[-1], side="right")
    dst = np.minimum(dst, n - 1).astype(np.int64)
    self_loop = dst == src
    dst[self_loop] = (dst[self_loop] + 1) % n
    return n, src.astype(np.int32), dst.astype(np.int32)


def twitter_csr(n: int, edge_factor: float = 35.0, seed: int = 11):
    """CSR form of the Twitter-2010-shaped proxy."""
    from janusgraph_tpu.olap.csr import csr_from_edges

    nv, src, dst = twitter_edges(n, edge_factor, seed=seed)
    return csr_from_edges(nv, src, dst)
