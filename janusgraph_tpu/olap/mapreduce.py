"""MapReduce stage over final vertex-program state.

Capability parity with the reference's map-reduce phase
(reference: graphdb/olap/computer/FulgoraGraphComputer.java:288-357 —
VertexMapJob per vertex emitting (key, value) into FulgoraMapEmitter,
WorkerPool-driven reduce via FulgoraReduceEmitter), re-designed as an
array operation: map() returns whole (keys, values) arrays, reduce is a
vectorized group-by with a monoid, finalize shapes the result.

Runs host-side on the result arrays — the reference's map-reduce is also a
host (JVM worker-pool) phase over the final vertex states.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from janusgraph_tpu.olap.vertex_program import Combiner


class MapReduce:
    """Subclass hooks: map() (required) + optionally finalize(), or override
    execute() outright for non-group-by reductions.

    memory_key — where the result lands in ComputerResult.memory
    reduce_op  — Combiner monoid for the default group-by reduce
    """

    memory_key: str = "mapreduce"
    reduce_op: str = Combiner.SUM

    def map(self, states: Dict[str, np.ndarray], csr, xp) -> Tuple[np.ndarray, np.ndarray]:
        """Return (keys, values) arrays of equal length (typically one entry
        per vertex; masked subsets allowed)."""
        raise NotImplementedError

    def finalize(self, result: Dict) -> object:
        return result

    def execute(self, states: Dict[str, np.ndarray], csr) -> object:
        keys, values = self.map(states, csr, np)
        keys = np.asarray(keys)
        values = np.asarray(values, dtype=np.float64)
        uniq, inverse = np.unique(keys, return_inverse=True)
        if self.reduce_op == Combiner.SUM:
            red = np.bincount(inverse, weights=values, minlength=len(uniq))
        elif self.reduce_op == Combiner.MIN:
            red = np.full(len(uniq), np.inf)
            np.minimum.at(red, inverse, values)
        else:
            red = np.full(len(uniq), -np.inf)
            np.maximum.at(red, inverse, values)
        return self.finalize(
            {k: v for k, v in zip(uniq.tolist(), red.tolist())}
        )


def run_map_reduce(mr: MapReduce, states: Dict[str, np.ndarray], csr) -> object:
    return mr.execute(states, csr)


# ------------------------------------------------------------ built-in jobs

class ClusterCountMapReduce(MapReduce):
    """Distinct cluster count + sizes from a label-valued state array
    (reference analogue: TinkerPop ClusterCountMapReduce /
    ClusterPopulationMapReduce used with peer pressure / CC)."""

    memory_key = "clusterCount"

    def __init__(self, state_key: str = "cluster"):
        self.state_key = state_key

    def map(self, states, csr, xp):
        labels = xp.asarray(states[self.state_key])
        return labels, xp.ones(len(labels))

    def finalize(self, result):
        return {"count": len(result), "sizes": result}


class StatsMapReduce(MapReduce):
    """min/max/mean/sum over one state array (reference analogue: the rank
    statistics map-reduces bundled with PageRank in TP3)."""

    memory_key = "stats"

    def __init__(self, state_key: str):
        self.state_key = state_key

    def execute(self, states, csr):
        v = np.asarray(states[self.state_key], dtype=np.float64)
        return {
            "min": float(v.min()),
            "max": float(v.max()),
            "mean": float(v.mean()),
            "sum": float(v.sum()),
            "count": int(len(v)),
        }


class TopKMapReduce(MapReduce):
    """Top-k vertices by a state value, as (vertex_id, value) pairs."""

    memory_key = "topK"

    def __init__(self, state_key: str, k: int = 10):
        self.state_key = state_key
        self.k = k

    def execute(self, states, csr):
        v = np.asarray(states[self.state_key], dtype=np.float64)
        k = min(self.k, len(v))
        idx = np.argpartition(-v, k - 1)[:k] if k else np.empty(0, dtype=int)
        idx = idx[np.argsort(-v[idx])]
        return [(int(csr.vertex_ids[i]), float(v[i])) for i in idx]
