"""Fulgora-analogue baseline: the reference's BSP architecture, timed.

The reference's OLAP engine executes vertex programs with a worker THREAD
POOL iterating vertex partitions — each thread calls the program per vertex
and sends messages through per-vertex HASH-MAP combiners (reference:
FulgoraGraphComputer.java:210-230 — numberOfWorkers threads over vertex
partitions inside a superstep barrier; FulgoraVertexMemory.java:91-99 —
concurrent map of combined incoming messages per vertex). No JVM exists in
this environment to time Fulgora itself (BASELINE.md), so this module IS
that architecture, re-built faithfully in Python: per-vertex scalar execute
loop, per-worker message dicts merged at the superstep barrier (the
python-idiomatic equivalent of the reference's atomic combine — and
slightly generous to the baseline, avoiding lock contention), BSP barrier,
memory aggregators.

Honesty note (recorded in the bench output): CPython threads share the GIL,
so the worker pool does not scale the way the JVM's does — the measured
number is per-vertex-hash-map architecture cost on one core times modest
thread overlap. The numpy proxy (bench.py host_pagerank_edges_per_sec)
remains the STRONG baseline for vs_baseline ratios; this one anchors the
architecture comparison the 50x north-star claim is framed against.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

import numpy as np


class FulgoraAnalogueComputer:
    """Threaded per-vertex BSP PageRank over a CSR snapshot.

    Semantics mirror PageRankProgram (olap/programs/pagerank.py) exactly —
    damping, dangling-mass redistribution — so results are comparable with
    the vectorized executors' output."""

    def __init__(self, csr, num_workers: int = 4):
        self.csr = csr
        self.num_workers = max(1, num_workers)

    def pagerank(
        self, iterations: int, damping: float = 0.85
    ) -> Tuple[np.ndarray, float]:
        """Run `iterations` supersteps; returns (rank, wall_seconds) where
        wall_seconds covers the supersteps only (setup excluded, matching
        how the vectorized executors are timed)."""
        csr = self.csr
        n = csr.num_vertices
        # adjacency as plain python structures: the per-vertex loop below
        # must see what Fulgora sees (object graphs, not arrays)
        out_indptr = csr.out_indptr
        out_dst = csr.out_dst.tolist()
        spans: List[Tuple[int, int]] = [
            (int(out_indptr[v]), int(out_indptr[v + 1])) for v in range(n)
        ]
        rank = [1.0 / n] * n
        out_deg = [hi - lo for lo, hi in spans]

        # vertex partitions, one per worker (reference: vertex partition
        # iterators handed to the worker pool)
        bounds = np.linspace(0, n, self.num_workers + 1).astype(int)
        partitions = [
            range(int(bounds[i]), int(bounds[i + 1]))
            for i in range(self.num_workers)
        ]

        t0 = time.perf_counter()
        for _ in range(iterations):
            # per-worker message maps; merged at the barrier (the
            # FulgoraVertexMemory combiner equivalent)
            worker_maps: List[Dict[int, float]] = [
                {} for _ in range(self.num_workers)
            ]
            dangling_parts = [0.0] * self.num_workers

            def execute_partition(wid: int, part) -> None:
                msgs = worker_maps[wid]
                dangling = 0.0
                for v in part:
                    lo, hi = spans[v]
                    if hi == lo:
                        dangling += rank[v]
                        continue
                    contrib = rank[v] / (hi - lo)
                    for e in range(lo, hi):
                        u = out_dst[e]
                        # hash-map SUM combiner (per-vertex slot)
                        msgs[u] = msgs.get(u, 0.0) + contrib
                dangling_parts[wid] = dangling

            threads = [
                threading.Thread(target=execute_partition, args=(w, p))
                for w, p in enumerate(partitions)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()  # the superstep barrier

            combined: Dict[int, float] = worker_maps[0]
            for m in worker_maps[1:]:
                for u, c in m.items():
                    combined[u] = combined.get(u, 0.0) + c
            dangling = sum(dangling_parts)

            base = (1.0 - damping) / n + damping * dangling / n
            new_rank = [base] * n
            for u, agg in combined.items():
                new_rank[u] = base + damping * agg
            rank = new_rank
        wall = time.perf_counter() - t0
        return np.asarray(rank), wall


def measure_fulgora_baseline(
    csr, iterations: int = 2, num_workers: int = 4
) -> Dict[str, float]:
    """Edges/s of the Fulgora-analogue at a given scale (few supersteps —
    per-superstep cost is constant, so edges/s extrapolates exactly)."""
    comp = FulgoraAnalogueComputer(csr, num_workers=num_workers)
    _rank, wall = comp.pagerank(iterations)
    return {
        "edges_per_sec": iterations * csr.num_edges / wall,
        "superstep_s": wall / iterations,
        "iterations": iterations,
        "num_workers": num_workers,
    }
