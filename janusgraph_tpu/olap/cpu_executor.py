"""CPU reference executor — the correctness oracle.

Mirrors the reference's Fulgora execution semantics
(reference: FulgoraGraphComputer.java:210-230 iteration loop with terminate
check, FulgoraVertexMemory double-buffered messages, combiner application on
send): messages are combined pairwise per receiving vertex in a plain Python
loop over in-edges — deliberately unvectorized and structurally independent
of the TPU executor, so agreement between the two is meaningful evidence
(SURVEY.md §7 step 4: "the correctness oracle").
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from janusgraph_tpu.olap.csr import CSRGraph
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    Memory,
    VertexProgram,
    apply_edge_transform,
)


def _combine(op: str, a, b):
    if op == Combiner.SUM:
        return a + b
    if op == Combiner.MIN:
        return np.minimum(a, b)
    return np.maximum(a, b)


class CPUExecutor:
    """Scalar-loop BSP executor (deliberately unvectorized).

    `strategy` (default "scalar") keeps the per-edge Python loop — the
    oracle. "ell" / "hybrid" instead run the SAME pack aggregation the
    device executors compile (olap/kernels.py is xp-generic), in numpy:
    the oracle side of the hybrid-vs-ELL bitwise-identity contract, and a
    vectorized host path when the scalar loop is too slow. Channel-switching
    supersteps always fall back to scalar delivery."""

    def __init__(self, graph: CSRGraph, strategy: str = "scalar", delta=None):
        if strategy not in ("scalar", "ell", "hybrid"):
            raise ValueError(f"unknown cpu strategy: {strategy!r}")
        self.strategy = strategy
        self._packs = {}
        self.graph = graph
        # delta-CSR overlay: consumed fused exactly like the device
        # executor (olap/delta.py is xp-generic), so cpu-fused vs
        # cpu-repacked stays inside the bitwise contract. Pack
        # strategies only — the scalar loop is the oracle for
        # MATERIALIZED snapshots instead.
        self._delta = delta if (delta is not None and delta.depth) else None
        self._fused_view = None
        if self._delta is not None:
            if strategy == "scalar":
                raise ValueError(
                    "delta-fused cpu runs require a pack strategy "
                    "('ell'/'hybrid'); the scalar oracle replays "
                    "materialized snapshots"
                )
            if graph.in_edge_weight is not None:
                raise ValueError(
                    "delta-fused runs support unfiltered weightless "
                    "snapshots only"
                )
            from janusgraph_tpu.olap.delta import FusedHostView

            self._fused_view = FusedHostView(self._delta)
        #: per-run execution record, same shape as TPUExecutor's — the
        #: CPU oracle reports the same roofline vocabulary (flops, bytes,
        #: operational intensity, utilization) so cost comparisons read
        #: uniformly; costs come from the host estimator (no XLA here)
        self.last_run_info: Dict[str, object] = {}

    def set_delta(self, delta) -> None:
        """Swap the pending-overlay view on a cached executor (the warm-
        submit executor-cache path, mirroring TPUExecutor.set_delta):
        the base graph and numpy packs survive across submits."""
        delta = delta if (delta is not None and delta.depth) else None
        if delta is None:
            self._delta = None
            self._fused_view = None
            return
        if self.strategy == "scalar":
            raise ValueError(
                "delta-fused cpu runs require a pack strategy "
                "('ell'/'hybrid'); the scalar oracle replays "
                "materialized snapshots"
            )
        if self.graph.in_edge_weight is not None:
            raise ValueError(
                "delta-fused runs support unfiltered weightless "
                "snapshots only"
            )
        if delta.csr is not self.graph:
            raise ValueError(
                "overlay view was built over a different base snapshot "
                "— a cached executor only serves overlays of ITS base "
                "CSR (the snapshot cache invalidates on compaction)"
            )
        from janusgraph_tpu.olap.delta import FusedHostView

        self._delta = delta
        self._fused_view = FusedHostView(delta)

    def run(
        self,
        program: VertexProgram,
        checkpoint_path: str = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        fault_hook=None,
        resume_attempts: int = 3,
        shard_checkpoint_dir: str = None,
        checkpoint_shards: int = 0,
    ) -> Dict[str, np.ndarray]:
        """Run to termination. Same checkpoint/auto-resume contract as
        TPUExecutor.run: save every `checkpoint_every` supersteps, and a
        SuperstepPreempted raised mid-run (the `fault_hook` consulted each
        superstep — e.g. FaultPlan.olap_hook) reloads the last checkpoint
        and replays, up to `resume_attempts` times. The replay recomputes
        the exact same numpy arithmetic from the saved arrays, so the
        final state is bitwise-identical to a fault-free run.

        `shard_checkpoint_dir` + `checkpoint_shards=S` write the SHARDED
        checkpoint format instead (per-shard slices + atomic manifest;
        olap/sharded_checkpoint.py) — the oracle side of the cross-shard
        format's executor-portability contract: a checkpoint written by
        the mesh executor restores here and vice versa."""
        from janusgraph_tpu.exceptions import SuperstepPreempted

        attempts = 0
        while True:
            try:
                return self._run(
                    program, checkpoint_path, checkpoint_every, resume,
                    fault_hook, shard_checkpoint_dir, checkpoint_shards,
                )
            except SuperstepPreempted:
                from janusgraph_tpu.observability import (
                    flight_recorder,
                    registry,
                )

                registry.counter("olap.preemptions").inc()
                if not (
                    (checkpoint_path or shard_checkpoint_dir)
                    and checkpoint_every
                ) or (attempts >= resume_attempts):
                    raise
                attempts += 1
                resume = True
                registry.counter("olap.resumes").inc()
                flight_recorder.record(
                    "olap_resume", executor="cpu", attempt=attempts,
                    program=type(program).__name__,
                    format="sharded" if shard_checkpoint_dir else "single",
                )

    def _run(
        self,
        program: VertexProgram,
        checkpoint_path: str,
        checkpoint_every: int,
        resume: bool,
        fault_hook,
        shard_checkpoint_dir: str = None,
        checkpoint_shards: int = 0,
    ) -> Dict[str, np.ndarray]:
        from janusgraph_tpu.olap.vertex_program import (
            check_weighted_transforms,
        )

        check_weighted_transforms(program, self.graph)
        if getattr(program, "message_mode", None) == "sddmm" and (
            program.undirected
        ):
            # mirror TPUExecutor: the sddmm row-dst builders cover the
            # in-CSR orientation only
            raise ValueError(
                "sddmm message mode aggregates over the in-CSR only — "
                "undirected dense programs are not supported"
            )
        if self._delta is not None:
            from janusgraph_tpu.olap.delta import (
                program_delta_compatible,
            )

            if not program_delta_compatible(program):
                raise ValueError(
                    "delta-fused runs support default-edge-view "
                    "programs only — materialize the overlay for this "
                    "program"
                )
        g = self.graph if self._delta is None else self._fused_view
        n = getattr(g, "local_num_vertices", g.num_vertices)
        memory = Memory()
        state = None
        start_step = 0
        if resume and (checkpoint_path or shard_checkpoint_dir):
            if shard_checkpoint_dir:
                from janusgraph_tpu.olap.sharded_checkpoint import (
                    load_sharded_checkpoint,
                )

                ck = load_sharded_checkpoint(shard_checkpoint_dir)
            else:
                from janusgraph_tpu.olap.checkpoint import load_checkpoint

                ck = load_checkpoint(checkpoint_path)
            if ck is not None:
                ck_state, ck_mem, start_step = ck
                state = {k: np.asarray(v) for k, v in ck_state.items()}
                memory.values = {k: float(v) for k, v in ck_mem.items()}
                memory.superstep = start_step
        if state is None:
            state, init_metrics = program.setup(g, np)
            memory.reduce_in(init_metrics)
            memory.superstep = 0
            start_step = 0

        import time as _time

        records = []
        for step in range(start_step, program.max_iterations):
            if fault_hook is not None:
                fault_hook(step)
            _s0 = _time.perf_counter()
            op = program.combiner_for(step)
            identity = Combiner.IDENTITY[op]
            ch_name = program.channel_for(step)
            use_pack = self.strategy != "scalar" and ch_name is None
            outgoing = np.asarray(
                program.message(state, step, g, np),
                # pack paths run float32 like the device executors (the
                # bitwise-identity contract); the oracle loop keeps f64
                dtype=np.float32 if use_pack else np.float64,
            )
            if use_pack:
                # the device executors' exact aggregation arithmetic
                # replayed in numpy (the errstate guard silences the
                # documented identity*0 transform noise the validity
                # mask then repairs)
                with np.errstate(invalid="ignore"):
                    if self._delta is not None:
                        from janusgraph_tpu.olap.delta import (
                            fused_delta_aggregate,
                        )

                        nb = self.graph.num_vertices
                        base_agg = self._pack_aggregate(
                            program, op, outgoing[:nb]
                        )
                        lanes = self._delta.lanes(
                            bool(program.undirected)
                        )
                        if lanes is None:
                            raise ValueError(
                                "delta overlay lanes exceed "
                                "computer.delta-max-lane-cells"
                            )
                        aggregated = fused_delta_aggregate(
                            np,
                            {k: v for k, v in lanes.items()
                             if not k.startswith("_")},
                            lanes["_meta"], outgoing, base_agg, op,
                        )
                    else:
                        aggregated = self._pack_aggregate(
                            program, op, outgoing
                        )
            vec = outgoing.ndim == 2
            if not use_pack:
                agg_shape = (n, outgoing.shape[1]) if vec else (n,)
                aggregated = np.full(agg_shape, identity, dtype=np.float64)

            sddmm = getattr(program, "message_mode", None) == "sddmm"

            def deliver(dst: int, src: int, weight):
                if sddmm:
                    # dense-tier dot-attention oracle: the per-edge
                    # coefficient is <h_src, h_dst> (f64 here — the scalar
                    # loop is the semantic oracle; the PACK strategies are
                    # the bitwise ones)
                    msg = outgoing[src] * float(
                        np.dot(outgoing[src], outgoing[dst])
                    )
                else:
                    msg = apply_edge_transform(
                        np, outgoing[src], weight,
                        program.edge_transform, program.edge_transform_cols,
                    )
                aggregated[dst] = _combine(op, aggregated[dst], msg)

            if use_pack:
                pass
            elif ch_name is not None:
                # typed edge view: deliver only along the channel's edges
                # (reference: per-scope slice queries,
                # VertexProgramScanJob.java:114-135)
                from janusgraph_tpu.olap.csr import channel_edges

                ch_src, ch_dst, ch_w = channel_edges(
                    g, program.edge_channels[ch_name]
                )
                for e in range(len(ch_src)):
                    w = float(ch_w[e]) if ch_w is not None else None
                    deliver(int(ch_dst[e]), int(ch_src[e]), w)
            else:
                for i in range(n):
                    for e in range(g.in_indptr[i], g.in_indptr[i + 1]):
                        w = g.in_edge_weight[e] if g.in_edge_weight is not None else None
                        deliver(i, int(g.in_src[e]), w)
                if program.undirected:
                    for i in range(n):
                        for e in range(g.out_indptr[i], g.out_indptr[i + 1]):
                            w = (
                                g.out_edge_weight[e]
                                if g.out_edge_weight is not None
                                else None
                            )
                            deliver(i, int(g.out_dst[e]), w)

            memory_in = dict(memory.values)
            state, metrics = program.apply(
                state, aggregated, step, memory_in, g, np
            )
            memory.reduce_in(metrics)
            records.append({
                "step": step,
                "wall_ms": round((_time.perf_counter() - _s0) * 1000.0, 3),
                "combiner": op,
            })
            steps_done = step + 1
            if (checkpoint_path or shard_checkpoint_dir) and (
                checkpoint_every
            ) and (
                steps_done % checkpoint_every == 0
                or steps_done == program.max_iterations
            ):
                _ck0 = _time.perf_counter()
                if shard_checkpoint_dir:
                    from janusgraph_tpu.olap.sharded_checkpoint import (
                        save_sharded_checkpoint,
                    )

                    save_sharded_checkpoint(
                        shard_checkpoint_dir,
                        {k: np.asarray(v) for k, v in state.items()},
                        memory.values,
                        steps_done,
                        max(1, checkpoint_shards),
                    )
                else:
                    from janusgraph_tpu.olap.checkpoint import (
                        save_checkpoint,
                    )

                    save_checkpoint(
                        checkpoint_path,
                        {k: np.asarray(v) for k, v in state.items()},
                        memory.values,
                        steps_done,
                    )
                # timeline marker (observability/timeline.py): the save's
                # wall, stamped on the superstep that paid it
                records[-1]["checkpoint_ms"] = round(
                    (_time.perf_counter() - _ck0) * 1000.0, 3
                )
            if program.terminate(memory):
                break
        self._publish_run(program, records)
        if self._delta is not None:
            # trim the vcap-tier padding (see TPUExecutor.run)
            return {
                k: np.asarray(v)[: self._delta.n_real]
                for k, v in state.items()
            }
        return {k: np.asarray(v) for k, v in state.items()}

    def _pack(self, undirected: bool):
        """ELL/Hybrid pack over the CPU graph's edge view (same layout the
        device executors build), cached per (strategy, orientation)."""
        key = (self.strategy, undirected)
        pack = self._packs.get(key)
        if pack is None:
            from janusgraph_tpu.olap.kernels import ELLPack, HybridPack

            g = self.graph
            n = g.num_vertices
            src = g.in_src.astype(np.int64)
            dst = np.repeat(
                np.arange(n, dtype=np.int64), np.diff(g.in_indptr)
            )
            w = g.in_edge_weight
            if undirected:
                src = np.concatenate([src, g.out_dst.astype(np.int64)])
                dst = np.concatenate([
                    dst,
                    np.repeat(
                        np.arange(n, dtype=np.int64), np.diff(g.out_indptr)
                    ),
                ])
                w = (
                    np.concatenate([w, g.out_edge_weight])
                    if w is not None
                    else None
                )
            cls = ELLPack if self.strategy == "ell" else HybridPack
            pack = cls(src, dst, w, n)
            self._packs[key] = pack
        return pack

    def _sddmm_rows(self, undirected: bool):
        """Row-destination vectors for the fused SDDMM pass, aligned with
        `_pack`'s layout — the numpy twins of TPUExecutor._sddmm_rows."""
        from janusgraph_tpu.olap.features import kernels as fkernels

        key = ("sddmm", self.strategy, undirected)
        rows = self._packs.get(key)
        if rows is None:
            g = self.graph
            n = g.num_vertices
            src = g.in_src.astype(np.int64)
            dst = np.repeat(
                np.arange(n, dtype=np.int64), np.diff(g.in_indptr)
            )
            if self.strategy == "ell":
                rows = fkernels.ell_row_dsts(src, dst, n)
            else:
                pack = self._pack(undirected)
                rows = fkernels.hybrid_row_dsts(
                    src, dst, n,
                    hub_cutoff=pack.hub_cutoff, tail_chunk=pack.tail_chunk,
                )
            self._packs[key] = rows
        return rows

    def _pack_aggregate(self, program: VertexProgram, op: str, outgoing):
        from janusgraph_tpu.olap.kernels import (
            ell_aggregate,
            hybrid_aggregate,
        )

        pack = self._pack(program.undirected)
        if getattr(program, "message_mode", None) == "sddmm":
            # dense tier: the same fused SDDMM+SpMM arithmetic the device
            # executor compiles, replayed in numpy (bitwise contract)
            from janusgraph_tpu.olap.features.kernels import (
                sddmm_ell_aggregate,
                sddmm_hybrid_aggregate,
            )

            rows = self._sddmm_rows(program.undirected)
            if self.strategy == "ell":
                return sddmm_ell_aggregate(np, pack, rows, outgoing, op)
            return sddmm_hybrid_aggregate(np, pack, rows, outgoing, op)
        agg_fn = ell_aggregate if self.strategy == "ell" else hybrid_aggregate
        return agg_fn(
            np, pack, outgoing, op, program.edge_transform,
            program.edge_transform_cols,
        )

    def _publish_run(self, program: VertexProgram, records) -> None:
        """Run record with the SAME roofline vocabulary as TPUExecutor
        (estimator costs: the scalar loop has no XLA to harvest). Host
        code only — nothing here is traced."""
        from janusgraph_tpu.observability import profiler, registry

        g = self.graph
        edges = g.num_edges * (2 if program.undirected else 1)
        cost = profiler.estimate_superstep_cost(
            g.num_vertices, edges,
            msg_cols=getattr(program, "d_pad", 1) or 1,
            weighted=g.in_edge_weight is not None,
        )
        peaks = profiler.device_peaks("cpu")
        tiers = profiler.attach_roofline(records, cost, peaks)
        info = {
            "path": "cpu",
            "supersteps": len(records),
            "wall_s": round(
                sum(r["wall_ms"] for r in records) / 1000.0, 4
            ),
            "superstep_records": records,
            "roofline_by_tier": tiers,
            "roofline": {
                "peak_flops": peaks["peak_flops"],
                "peak_bytes_per_s": peaks["peak_bytes_per_s"],
                "device_kind": peaks["device_kind"],
                "peaks_source": peaks["source"],
            },
            # same cost vocabulary as the OLTP profile resources block;
            # the scalar loop moves no device bytes
            "resources": {
                "h2d_bytes": 0,
                "d2h_bytes": 0,
                "flops": sum(r.get("flops", 0.0) for r in records),
                "bytes_accessed": sum(
                    r.get("bytes_accessed", 0.0) for r in records
                ),
            },
        }
        # dense tier: same per-superstep MXU accounting as the device
        # executor, so utilization comparisons read uniformly
        if callable(getattr(program, "matmul_flops", None)):
            per_step = float(program.matmul_flops(g.num_vertices, edges))
            info["mxu"] = profiler.attach_mxu(records, per_step, peaks)
        self.last_run_info = info
        registry.record_run("olap", info)
