"""Distributed bulk input format + distributed index management.

Capability parity with the reference's Hadoop integration
(reference: janusgraph-hadoop .../formats/util/HadoopInputFormat.java:34 +
HadoopRecordReader.java:111 — partition the edgestore into input splits and
deserialize raw rows into star vertices via
JanusGraphVertexDeserializer.java; MapReduceIndexManagement.java:276 — run
index repair/remove jobs across splits at cluster scale).

TPU-first re-design: splits are ID-partition ranges (the same structure the
device mesh shards by — IDManager.partition_key_range), records are
`StarVertex` (adjacency + properties of one vertex), and the cluster-scale
consumers are (a) per-shard CSR loading for the sharded executor and
(b) a worker-parallel distributed reindex driver. An external engine (or a
multi-host launcher) can consume splits independently: each split reads
only its own key range.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.storage.kcvs import KeyRangeQuery, KeySliceQuery, SliceQuery


@dataclass
class StarVertex:
    """One vertex with its full adjacency star (reference: TinkerPop
    StarVertex as produced by JanusGraphVertexDeserializer)."""

    vertex_id: int
    label: str = "vertex"
    properties: Dict[str, List[object]] = field(default_factory=dict)
    #: out-edges as (edge_label, other_vertex_id, edge_properties)
    edges: List[Tuple[str, int, Dict[str, object]]] = field(default_factory=list)


@dataclass(frozen=True)
class InputSplit:
    """A unit of distributed read work: one contiguous ID-partition range
    (reference: HadoopInputFormat.getSplits — one split per token range)."""

    split_id: int
    partitions: Tuple[int, ...]


class GraphInputFormat:
    """Splits + record reading over a graph's edgestore."""

    def __init__(self, graph):
        self.graph = graph
        self.idm = graph.idm
        self.es = graph.edge_serializer
        self.st = graph.system_types

    def splits(self, num_splits: Optional[int] = None) -> List[InputSplit]:
        """Group the ID partitions into `num_splits` splits (defaults to one
        split per partition)."""
        nparts = self.idm.num_partitions
        if num_splits is None or num_splits >= nparts:
            return [InputSplit(p, (p,)) for p in range(nparts)]
        num_splits = max(1, num_splits)
        out: List[InputSplit] = []
        for s in range(num_splits):
            parts = tuple(range(nparts))[s::num_splits]
            if parts:
                out.append(InputSplit(s, parts))
        return out

    # ------------------------------------------------------------- reading
    def read_split(self, split: InputSplit) -> Iterator[StarVertex]:
        """Deserialize every live vertex row in the split into a StarVertex
        (reference: HadoopRecordReader -> JanusGraphVertexDeserializer)."""
        g = self.graph
        btx = g.backend.begin_transaction()
        store_tx = btx.store_tx
        store = g.backend.edgestore
        schema = _codec_schema(g)
        exists_q = self.es.get_type_slice(self.st.EXISTS, False)
        label_q = self.es.get_type_slice(
            self.st.VERTEX_LABEL_EDGE, True, Direction.OUT
        )
        prop_q, edge_q = self.es.user_relations_bounds()
        ordered = g.backend.manager.features.ordered_scan
        ranges = [self.idm.partition_key_range(p) for p in split.partitions]

        def rows():
            if ordered:
                for start, end in ranges:
                    yield from store.get_keys(
                        KeyRangeQuery(start, end, exists_q), store_tx
                    )
            else:
                for key, entries in store.get_keys(exists_q, store_tx):
                    if any(s <= key < e for s, e in ranges):
                        yield key, entries

        for key, _exist in rows():
            vid = self.idm.get_vertex_id(key)
            if not self.idm.is_user_vertex_id(vid):
                continue
            sv = StarVertex(vertex_id=self.idm.get_canonical_vertex_id(vid))
            # label
            for e in store.get_slice(KeySliceQuery(key, label_q), store_tx):
                rc = self.es.parse_relation(e, self.st.type_info)
                el = g.schema_cache.get_by_id(rc.other_vertex_id)
                if el is not None:
                    sv.label = el.name
            # properties
            for e in store.get_slice(KeySliceQuery(key, prop_q), store_tx):
                try:
                    rc = self.es.parse_relation(e, schema)
                except KeyError:
                    continue
                pk = g.schema_cache.get_by_id(rc.type_id)
                if pk is not None:
                    sv.properties.setdefault(pk.name, []).append(rc.value)
            # out-edges
            relidx_ids = getattr(g, "relation_index_ids", frozenset())
            for e in store.get_slice(KeySliceQuery(key, edge_q), store_tx):
                try:
                    rc = self.es.parse_relation(e, schema)
                except KeyError:
                    continue
                if not rc.is_edge or rc.direction != Direction.OUT:
                    continue
                if rc.type_id in relidx_ids:
                    continue  # RelationTypeIndex copies are not edges
                el = g.schema_cache.get_by_id(rc.type_id)
                props = {}
                if rc.properties:
                    for tid, val in rc.properties.items():
                        pk = g.schema_cache.get_by_id(tid)
                        if pk is not None:
                            props[pk.name] = val
                sv.edges.append(
                    (el.name if el else str(rc.type_id), rc.other_vertex_id, props)
                )
            yield sv

    def read_all(self, num_splits: Optional[int] = None) -> Iterator[StarVertex]:
        for split in self.splits(num_splits):
            yield from self.read_split(split)


def load_shard_csrs(graph, num_shards: int):
    """One CSRGraph per shard of ID partitions — the bulk path feeding each
    mesh device/host its own slice (reference: backend-specific binary input
    formats feeding SparkGraphComputer executors)."""
    from janusgraph_tpu.olap.csr import load_csr

    fmt = GraphInputFormat(graph)
    return [
        load_csr(graph, partitions=list(split.partitions))
        for split in fmt.splits(num_shards)
    ]


def _codec_schema(graph):
    def lookup(type_id: int):
        info = graph.system_types.type_info(type_id)
        if info is not None:
            return info
        el = graph.schema_cache.get_by_id(type_id)
        if el is None:
            raise KeyError(type_id)
        return el.type_info()

    return lookup


class DistributedIndexManagement:
    """Worker-parallel index maintenance across input splits
    (reference: MapReduceIndexManagement.java:276 running IndexRepairJob /
    IndexRemoveJob as Hadoop MR jobs)."""

    def __init__(self, graph, num_workers: int = 4):
        self.graph = graph
        self.num_workers = num_workers

    def reindex(self, index_name: str):
        """REINDEX across splits in parallel; returns merged ScanMetrics."""
        from janusgraph_tpu.olap.jobs import IndexRepairJob
        from janusgraph_tpu.storage.scan import ScanMetrics, StandardScanner

        g = self.graph
        idx = g.indexes.get(index_name)
        if idx is None:
            raise KeyError(f"no index named {index_name!r}")
        fmt = GraphInputFormat(g)
        splits = fmt.splits(self.num_workers)
        merged = ScanMetrics()

        def run_split(split: InputSplit) -> ScanMetrics:
            job = IndexRepairJob(g, idx)
            btx = g.backend.begin_transaction()
            scanner = StandardScanner(
                g.backend.edgestore,
                btx.store_tx,
                ordered_scan=g.backend.manager.features.ordered_scan,
            )
            ranges = [
                g.idm.partition_key_range(p) for p in split.partitions
            ]
            return scanner.execute(job, key_ranges=ranges, num_workers=1)

        from janusgraph_tpu.observability import capture_scope

        # pool workers start from an empty contextvars context; without
        # the capture the per-split scan spans detach from the caller's
        # trace and ledger/deadline attribution is lost (JG402)
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            for metrics in pool.map(capture_scope(run_split), splits):
                merged.merge(metrics)
        return merged
