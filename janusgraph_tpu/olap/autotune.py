"""Profiler-driven autotuner: close the measurement -> kernel-choice loop.

PR 5 made superstep cost visible (XLA ``cost_analysis`` flops/bytes,
%-roofline per E_cap tier, pad ratios in every run record); this module
CONSUMES it. Given a graph's degree statistics, the device kind's roofline
peaks (observability/profiler.py), the ``computer.autotune-*`` config
overrides, and optionally a prior run's measurements, it decides:

  * the aggregation **strategy** — ``ell`` (pow2 degree buckets),
    ``hybrid`` (exact-width torso + chunked CSR tail, olap/kernels.py
    HybridPack), or ``segment`` (flat gather + segment reduce when any
    packed layout blows the HBM budget);
  * the hybrid **hub cutoff** and **tail chunk** (searched over pow2
    candidates against a bytes/peak_bw + flops/peak_flops time model);
  * the frontier **tier schedules** (F_cap/E_cap ladders) for the
    ShortestPath/CC special case — sized from the degree histogram and a
    tier-count budget instead of today's fixed power-of-two growth.

Decisions are DETERMINISTIC: ``decide()`` is a pure function of
(GraphStats, device_kind, overrides, measured) — same inputs, same
AutotuneDecision, asserted by tests. The executor records the decision in
``run_info["autotune"]`` and the bench artifact carries it per stage.

The graph-kernel literature motivates both levers (PAPERS.md):
arXiv:2011.08451 (propagation blocking) shows format/preprocessing choice
dominates graph-kernel bandwidth; arXiv:2011.06391 (FusedMM) shows one
tuned kernel shape serves many workloads once the layout is right.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np


def _next_pow2(v: int) -> int:
    return 1 << max(0, int(v) - 1).bit_length() if v > 1 else 1


#: pow2 hub-cutoff candidates the model searches (bounded so stats stay
#: small and the decision cheap)
CUTOFF_CANDIDATES = tuple(1 << k for k in range(3, 11))  # 8 .. 1024


@dataclass(frozen=True)
class GraphStats:
    """Degree-distribution summary the tuner decides from. Everything is
    precomputed here (one numpy pass over the degree vector) so
    ``decide()`` itself is pure integer/float arithmetic."""

    num_vertices: int
    num_edges: int          # per packed orientation (2x |E| when undirected)
    weighted: bool
    max_degree: int
    mean_degree: float
    #: log2-bucket in-degree histogram: hist[k] = #vertices with
    #: 2^(k-1) < deg <= 2^k (hist[0] = deg 0 plus deg 1)
    degree_hist: Tuple[int, ...]
    #: pure-ELL slot count (pow2 bucket rounding, supernode row-split)
    ell_slots: int
    #: candidate hub cutoff -> (cutoff, hybrid gathered slots, hub count,
    #: torso bucket count, tail chunk rows) — the closed-form HybridPack
    #: footprint per cutoff; chunk rows price the tail's partial-table
    #: scatter, the term that punishes small chunks (measured s18 sweep:
    #: 132k chunks = 23.8 ms/superstep vs 6k chunks = 14.4 ms at equal pad)
    hybrid_by_cutoff: Tuple[Tuple[int, int, int, int, int], ...]

    @classmethod
    def from_degrees(
        cls, deg: np.ndarray, num_edges: int, weighted: bool,
        max_capacity: int = 1 << 14, tail_chunk: int = 256,
    ) -> "GraphStats":
        deg = np.asarray(deg, dtype=np.int64)
        n = len(deg)
        maxd = int(deg.max()) if n else 0
        caps = np.maximum(
            1, 1 << np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
        )
        capped = np.minimum(caps, max_capacity)
        ell_slots = int(capped.sum())
        over = deg > max_capacity
        if over.any():
            ell_slots += int((deg[over] - max_capacity).sum())
        hist_bins = np.zeros(36, dtype=np.int64)
        if n:
            k = np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
            np.add.at(hist_bins, np.minimum(k, 35), 1)
        hyb = []
        for cutoff in CUTOFF_CANDIDATES:
            torso = (deg >= 1) & (deg <= cutoff)
            hub = deg > cutoff
            t = min(tail_chunk, _next_pow2(cutoff + 1), max_capacity)
            chunk_rows = int((-(-deg[hub] // t)).sum())
            slots = int(deg[torso].sum()) + chunk_rows * t
            torso_buckets = int(len(np.unique(deg[torso]))) if torso.any() else 0
            hyb.append(
                (cutoff, slots, int(hub.sum()), torso_buckets, chunk_rows)
            )
        return cls(
            num_vertices=n,
            num_edges=int(num_edges),
            weighted=bool(weighted),
            max_degree=maxd,
            mean_degree=float(num_edges) / n if n else 0.0,
            degree_hist=tuple(int(x) for x in np.trim_zeros(hist_bins, "b")),
            ell_slots=ell_slots,
            hybrid_by_cutoff=tuple(hyb),
        )

    @classmethod
    def from_csr(cls, csr, undirected: bool = False, **kw) -> "GraphStats":
        deg = np.diff(csr.in_indptr).astype(np.int64)
        edges = csr.num_edges
        if undirected:
            deg = deg + np.diff(csr.out_indptr).astype(np.int64)
            edges *= 2
        return cls.from_degrees(
            deg, edges, weighted=csr.in_edge_weight is not None, **kw
        )


@dataclass(frozen=True)
class AutotuneDecision:
    """One deterministic tuning decision. ``as_dict()`` is the record shape
    stored in ``run_info["autotune"]`` and bench artifacts."""

    strategy: str                     # ell | hybrid | segment
    hub_cutoff: Optional[int]         # hybrid only
    tail_chunk: Optional[int]         # hybrid only
    pad_ratio_est: float              # chosen layout's modeled pad ratio
    f_schedule: Tuple[int, ...]       # frontier F_cap ladder (pow2, asc)
    e_schedule: Tuple[int, ...]       # frontier E_cap ladder (pow2, asc)
    device_kind: str
    source: str                       # model | config | measured+model
    modeled_ms: Dict[str, float] = field(default_factory=dict)
    #: dense-feature tier input: the program's logical/padded feature dim
    #: (0/None for scalar-message programs)
    feature_dim: int = 0
    feature_tier: Optional[int] = None

    def as_dict(self) -> dict:
        return {
            "strategy": self.strategy,
            "hub_cutoff": self.hub_cutoff,
            "tail_chunk": self.tail_chunk,
            "pad_ratio_est": round(self.pad_ratio_est, 4),
            "f_schedule": list(self.f_schedule),
            "e_schedule": list(self.e_schedule),
            "device_kind": self.device_kind,
            "source": self.source,
            "feature_dim": self.feature_dim,
            "feature_tier": self.feature_tier,
            "modeled_ms": {
                k: round(v, 4) for k, v in sorted(self.modeled_ms.items())
            },
        }


#: bytes gathered per slot: idx i32; weighted packs add weight+valid f32
def _bytes_per_slot(weighted: bool) -> int:
    return 12 if weighted else 4


#: modeled fixed cost per distinct device kernel (gather+fold per bucket).
#: Small: XLA fuses the per-bucket gathers into one program, so even
#: hundreds of exact-width torso buckets barely register (measured s18:
#: the 555-torso-bucket config was among the FASTEST)
_BUCKET_OVERHEAD_S = 2e-7

#: modeled cost per hybrid tail chunk row: each chunk pays a partial-table
#: scatter element + fold slot on top of its gathered bytes. Calibrated
#: from the s18 sweep (126k extra chunks cost ~9.4 ms => ~75 ns/chunk on
#: host XLA; TPUs scatter relatively better)
_TAIL_CHUNK_COST_S = {"cpu": 7.5e-8, "tpu": 3e-8}

#: measured per-gathered-slot cost of the packed aggregation kernels —
#: the gather unit is the binding resource, well below what the DRAM-peak
#: bytes/bw term predicts. cpu: ~3.3 ns/slot (s18 sweep this round, both
#: layouts); tpu: the ~140M gathered elem/s v5e gather wall
#: (docs/tpu_notes.md) => ~7 ns/slot
_GATHER_COST_S = {"cpu": 3.3e-9, "tpu": 7e-9}

#: scatter (segment-reduce) effective-bandwidth derating vs the packed
#: gather paths — the reason ELL exists at all (serialized scatter-add
#: lowering on TPU; cache-hostile on CPU)
_SEGMENT_PENALTY = {"tpu": 8.0, "cpu": 2.5}


def _modeled_seconds(
    slots: int, n: int, weighted: bool, buckets: int, peaks: dict,
    penalty: float = 1.0, eff_bw: Optional[float] = None,
    chunk_rows: int = 0, kind: str = "cpu", cols: int = 1,
) -> float:
    """Roofline time model for one superstep of a packed aggregation: the
    binding constraint is max(bytes moved at peak-or-measured bandwidth,
    slots through the gather unit) — the classic two-ceiling roof with the
    gather wall as the second ceiling — plus per-bucket kernel overhead
    and the tail's per-chunk scatter cost. ``cols`` is the message width
    (1 for scalar programs; the padded feature tier for the dense tier —
    each gathered slot moves a d-wide row and the output is (n, d))."""
    cols = max(1, int(cols))
    bw = eff_bw or peaks["peak_bytes_per_s"]
    byts = slots * _bytes_per_slot(weighted) + 4.0 * slots * cols + (
        8.0 * n * cols
    )
    t = max(
        penalty * byts / max(bw, 1.0),
        penalty * slots * _GATHER_COST_S[kind],
    )
    t += slots * cols / max(peaks["peak_flops"], 1.0)
    t += buckets * _BUCKET_OVERHEAD_S
    # the tail's partial-table scatter moves a cols-wide row per chunk, so
    # its cost scales with the message width (measured r7: s16 d=32 GCN,
    # hybrid 276.8 ms vs ELL 190.9 ms per superstep — the scatter term is
    # what flips the winner for dense-feature runs)
    t += chunk_rows * cols * _TAIL_CHUNK_COST_S[kind]
    return t


def decide(
    stats: GraphStats,
    device_kind: str,
    overrides: Optional[dict] = None,
    measured: Optional[dict] = None,
    feature_dim: int = 0,
) -> AutotuneDecision:
    """Pick (strategy, hub cutoff, tail chunk, tier schedules) for one
    graph + device. Pure function of its arguments — identical inputs give
    an identical decision (tested), so a recorded decision is reproducible
    from its recorded inputs.

    overrides (the ``computer.autotune-*`` / legacy budget keys):
      strategy          force the strategy outright (source="config")
      hub_cutoff        force the hybrid cutoff (0/None = search)
      tail_chunk        tail chunk width (default 128)
      min_gain          fractional modeled-time gain hybrid must show over
                        ELL before it is chosen (default 0.05)
      budget_bytes      HBM budget for packed layouts (default 6 GiB)
      max_pad           pad-ratio ceiling for packed layouts (default 3.0)
      f_min/e_min       smallest frontier tier capacities
      max_tiers         frontier ladder length budget (default 8)
      tier_growth       max ladder growth factor (pow2, default 16)

    measured (a prior run's record — ``registry.last_run("olap")`` shape):
      ``pad_ratio`` + ``superstep_ms`` of a run with ``strategy`` calibrate
      the model's effective bandwidth (achieved bytes/s replaces the peak
      table), folding real measurements into the next decision;
      ``roofline_by_tier`` utilizations refine the frontier ladder (tiers
      that measured near-zero utilization are pruned from the schedule).

    feature_dim (the dense tier's input, 0 for scalar programs): the
      padded lane tier (features/kernels.pick_feature_tier, or the
      ``feature_dim_tier`` override) scales the modeled message traffic —
      every gathered slot moves a d-wide row — and is recorded in the
      decision as ``feature_tier``.
    """
    ov = dict(overrides or {})
    from janusgraph_tpu.observability import profiler

    peaks = profiler.device_peaks(device_kind)
    kind = "tpu" if "tpu" in (device_kind or "").lower() else "cpu"
    budget = int(ov.get("budget_bytes") or (6 << 30))
    max_pad = float(ov.get("max_pad") or 3.0)
    min_gain = float(ov.get("min_gain") if ov.get("min_gain") is not None
                     else 0.05)
    tail_chunk = int(ov.get("tail_chunk") or 256)
    feature_dim = int(feature_dim or 0)
    feature_tier = None
    cols = 1
    if feature_dim:
        from janusgraph_tpu.olap.features.kernels import pick_feature_tier

        feature_tier = pick_feature_tier(
            feature_dim, int(ov.get("feature_dim_tier") or 0)
        )
        cols = feature_tier

    n, m = stats.num_vertices, stats.num_edges
    bps = _bytes_per_slot(stats.weighted)

    # measured calibration: achieved bytes/s of the prior run's layout
    eff_bw = None
    source = "model"
    if measured and measured.get("superstep_ms") and measured.get("pad_ratio"):
        meas_slots = float(measured["pad_ratio"]) * m
        meas_bytes = meas_slots * bps + 4.0 * meas_slots * cols + (
            8.0 * n * cols
        )
        eff_bw = meas_bytes / (float(measured["superstep_ms"]) / 1e3)
        source = "measured+model"

    # candidate models ----------------------------------------------------
    modeled: Dict[str, float] = {}
    modeled["segment"] = _modeled_seconds(
        m, n, stats.weighted, 1, peaks,
        penalty=_SEGMENT_PENALTY[kind], eff_bw=eff_bw, cols=cols,
    )
    ell_buckets = max(1, len(stats.degree_hist))
    ell_pad = stats.ell_slots / max(1, m)
    modeled["ell"] = _modeled_seconds(
        stats.ell_slots, n, stats.weighted, ell_buckets, peaks,
        eff_bw=eff_bw, cols=cols,
    )

    forced_cutoff = int(ov.get("hub_cutoff") or 0) or None
    best = None  # (modeled_s, cutoff, slots)
    for cutoff, slots, hubs, torso_buckets, chunk_rows in (
        stats.hybrid_by_cutoff
    ):
        if forced_cutoff is not None and cutoff != forced_cutoff:
            continue
        t = _modeled_seconds(
            slots, n, stats.weighted,
            torso_buckets + (1 if hubs else 0), peaks, eff_bw=eff_bw,
            chunk_rows=chunk_rows, kind=kind, cols=cols,
        )
        if best is None or t < best[0]:
            best = (t, cutoff, slots)
    if best is not None:
        modeled["hybrid"] = best[0]
        hyb_cutoff, hyb_slots = best[1], best[2]
        hyb_pad = hyb_slots / max(1, m)
    else:
        hyb_cutoff, hyb_slots, hyb_pad = None, stats.ell_slots, ell_pad

    # strategy choice -----------------------------------------------------
    forced = ov.get("strategy")
    if forced and forced not in ("auto",):
        strategy, source = forced, "config"
    else:
        strategy = "ell"
        if "hybrid" in modeled and modeled["hybrid"] < modeled["ell"] * (
            1.0 - min_gain
        ):
            strategy = "hybrid"
        chosen_slots = hyb_slots if strategy == "hybrid" else stats.ell_slots
        chosen_pad = hyb_pad if strategy == "hybrid" else ell_pad
        if chosen_slots * bps > budget or chosen_pad > max_pad:
            strategy = "segment"

    pad_est = {
        "ell": ell_pad, "hybrid": hyb_pad, "segment": 1.0, "pallas": 1.0,
    }.get(strategy, ell_pad)

    f_sched, e_sched = decide_tiers(stats, ov, measured)
    return AutotuneDecision(
        strategy=strategy,
        hub_cutoff=hyb_cutoff if strategy == "hybrid" else None,
        tail_chunk=(
            min(tail_chunk, _next_pow2((hyb_cutoff or 0) + 1))
            if strategy == "hybrid" and hyb_cutoff
            else (tail_chunk if strategy == "hybrid" else None)
        ),
        pad_ratio_est=float(pad_est),
        f_schedule=f_sched,
        e_schedule=e_sched,
        device_kind=device_kind or "cpu",
        source=source,
        feature_dim=feature_dim,
        feature_tier=feature_tier,
        modeled_ms={k: v * 1e3 for k, v in modeled.items()},
    )


#: modeled launch latency per message-carrying collective (one batch) —
#: the term that punishes the ring's S-1 ppermute batches per superstep
_COLLECTIVE_LAUNCH_S = {"cpu": 2e-5, "tpu": 5e-6}


@dataclass(frozen=True)
class ShardedDecision:
    """One deterministic per-shard-layout decision (the mesh analogue of
    AutotuneDecision), keyed by shard count. ``as_dict()`` is the record
    shape stored in ``run_info["autotune"]`` on sharded runs."""

    exchange: str                 # blocked | a2a | ring | gather
    agg: str                      # ell | segment
    halo_cap: int                 # pow2 bin tier (blocked exchange)
    boundary_width: int           # eager a2a bucket width B
    shard_count: int
    device_kind: str
    source: str                   # model | config | measured+model
    modeled_ms: Dict[str, float] = field(default_factory=dict)
    feature_tier: Optional[int] = None

    def as_dict(self) -> dict:
        return {
            "exchange": self.exchange,
            "agg": self.agg,
            "halo_cap": self.halo_cap,
            "boundary_width": self.boundary_width,
            "shard_count": self.shard_count,
            "device_kind": self.device_kind,
            "source": self.source,
            "feature_tier": self.feature_tier,
            "modeled_ms": {
                k: round(v, 4) for k, v in sorted(self.modeled_ms.items())
            },
        }


def decide_sharded(
    stats: GraphStats,
    device_kind: str,
    num_shards: int,
    widths: dict,
    overrides: Optional[dict] = None,
    measured: Optional[dict] = None,
    feature_dim: int = 0,
) -> ShardedDecision:
    """Pick the sharded executor's per-shard layout — exchange strategy +
    aggregation + pow2 halo-bin tier — for one (graph, device, SHARD
    COUNT). Pure function of its arguments (tested), so a recorded
    decision is reproducible from its recorded inputs.

    ``widths`` is halo.pair_widths' output: the eager boundary width B
    (distinct cross-shard sources any pair ships) vs the blocked halo
    width (distinct cross-shard destinations any pair merges into) plus
    the pow2 ``halo_cap`` tier.

    The per-superstep model per shard: local aggregation work (slots
    through the gather unit, ELL pays its pad ratio, blocked adds the
    S*Hc receiver scatter-combine), exchange payload at peak-or-measured
    bandwidth, and a launch cost per message-carrying collective — the
    term that charges the ring its S-1 batches. ``measured`` (the v2
    shard-count-keyed record) calibrates effective bandwidth exactly like
    ``decide()``; an explicit ``overrides["exchange"]`` forces the layout
    (source="config")."""
    ov = dict(overrides or {})
    from janusgraph_tpu.observability import profiler

    peaks = profiler.device_peaks(device_kind)
    kind = "tpu" if "tpu" in (device_kind or "").lower() else "cpu"
    S = max(1, int(num_shards))
    n, m = stats.num_vertices, stats.num_edges
    Np = -(-max(n, 1) // S)
    Em = max(1, m // S)
    cols = 1
    feature_tier = None
    if feature_dim:
        from janusgraph_tpu.olap.features.kernels import pick_feature_tier

        feature_tier = pick_feature_tier(int(feature_dim), 0)
        cols = feature_tier
    B = max(1, int(widths.get("boundary_width") or 1))
    Hc = max(1, int(widths.get("halo_cap") or 1))

    bw = peaks["peak_bytes_per_s"]
    source = "model"
    if measured and measured.get("superstep_ms"):
        # achieved bytes/s of the prior run's layout at this shard count
        meas_bytes = Em * (4.0 + 4.0 * cols) + 8.0 * Np * cols
        eff = meas_bytes / (float(measured["superstep_ms"]) / 1e3)
        bw = max(min(bw, eff), 1.0)
        source = "measured+model"

    gcost = _GATHER_COST_S[kind]
    launch = _COLLECTIVE_LAUNCH_S[kind]
    elem_bytes = 4.0 * cols

    def t_exchange(elems: int, batches: int) -> float:
        return elems * elem_bytes / max(bw, 1.0) + batches * launch

    ell_slots_per_shard = max(1, stats.ell_slots // S)
    modeled: Dict[str, float] = {
        # eager a2a + uniform ELL: padded gather slots + table concat
        "a2a-ell": (
            ell_slots_per_shard * gcost * cols
            + (Np + S * B) * elem_bytes / max(bw, 1.0)
            + t_exchange(S * B, 1)
        ),
        # eager a2a + flat segment: exact slots, scatter derating
        "a2a-segment": (
            Em * gcost * cols * _SEGMENT_PENALTY[kind] / 2.0
            + (Np + S * B) * elem_bytes / max(bw, 1.0)
            + t_exchange(S * B, 1)
        ),
        # propagation-blocked + packed merge: ELL slots gathered from the
        # shard's OWN Np-row block (no table concat, cache-resident),
        # S*Hc merged elements on the wire, one width-R receiver combine
        "blocked-ell": (
            ell_slots_per_shard * gcost * cols
            + (S * Hc) * gcost * cols
            + t_exchange(S * Hc, 1)
        ),
        # propagation-blocked + fused scatter merge: exact slots, one
        # segment reduction covering local dsts AND outgoing bins
        "blocked-segment": (
            (Em + S * Hc) * gcost * cols
            * _SEGMENT_PENALTY[kind] / 2.0
            + t_exchange(S * Hc, 1)
        ),
        # ring streaming: S-1 ppermute batches of one Np block each
        "ring-segment": (
            Em * gcost * cols * _SEGMENT_PENALTY[kind] / 2.0
            + t_exchange((S - 1) * Np, S - 1)
        ),
        # debug reference: the full padded vector every superstep
        "gather-segment": (
            Em * gcost * cols * _SEGMENT_PENALTY[kind] / 2.0
            + t_exchange(S * Np, 1)
        ),
    }

    forced = ov.get("exchange")
    if forced and forced not in ("auto",):
        agg_for = {
            "blocked": ov.get("agg") or "ell",
            "a2a": ov.get("agg") or "ell",
            "ring": "segment", "gather": "segment",
        }
        choice = f"{forced}-{agg_for.get(forced, 'segment')}"
        source = "config"
    else:
        choice = min(modeled, key=lambda k: (modeled[k], k))
    exchange, agg = choice.split("-", 1)
    return ShardedDecision(
        exchange=exchange,
        agg=agg,
        halo_cap=Hc,
        boundary_width=B,
        shard_count=S,
        device_kind=device_kind or "cpu",
        source=source,
        modeled_ms={k: v * 1e3 for k, v in modeled.items()},
        feature_tier=feature_tier,
    )


@dataclass(frozen=True)
class DeltaDecision:
    """Deterministic delta-vs-repack compaction decision for the
    incremental delta-CSR overlay (olap/delta.py): at what overlay depth
    does folding the overlay back into the base pack beat carrying the
    fused lanes through every superstep."""

    compact_threshold: int
    device_kind: str
    source: str                      # model | config
    cells: Dict[str, float] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "compact_threshold": self.compact_threshold,
            "device_kind": self.device_kind,
            "source": self.source,
            "cells": {
                k: round(v, 9) for k, v in sorted(self.cells.items())
            },
        }


#: per-record per-superstep cost of the fused delta lanes (one gathered
#: slot + one segment-scatter element per lane entry; the scatter side is
#: the binding one — same derating family as _SEGMENT_PENALTY)
_DELTA_LANE_COST_S = {"cpu": 8e-9, "tpu": 5.5e-8}

#: per-edge cost of the zero-scan materialize (numpy multiset merge +
#: native CSR rebuild) — measured slope of olap/delta.materialize on this
#: container (~25 ns/edge at s16-s20); the full scan+decode repack is
#: ~14x that (r05: 5.6 s at s20/16M edges => ~350 ns/edge)
_DELTA_MATERIALIZE_COST_S = 2.5e-8
_REPACK_SCAN_COST_S = 3.5e-7


def decide_delta(
    num_edges: int,
    num_vertices: int,
    device_kind: str = "cpu",
    overrides: Optional[dict] = None,
    expected_runs: int = 8,
) -> DeltaDecision:
    """Pure function of (graph size, device kind, overrides) -> the
    overlay depth at which compaction amortizes: an overlay of depth d
    costs ~d lane cells per superstep per run, while folding it costs one
    O(E) zero-scan materialize. The threshold solves
    ``expected_runs * supersteps * d * lane_cost >= materialize_cost``
    and is clamped to a pow2 in [1024, 65536] so the fused lanes' tier
    ladder stays short. ``overrides={"compact_threshold": n}`` wins
    (config computer.delta-compact-threshold)."""
    ov = overrides or {}
    if ov.get("compact_threshold"):
        return DeltaDecision(
            compact_threshold=int(ov["compact_threshold"]),
            device_kind=device_kind, source="config",
        )
    kind = "tpu" if "tpu" in str(device_kind).lower() else "cpu"
    supersteps = 20.0  # a PageRank-shaped run's typical iteration count
    lane = _DELTA_LANE_COST_S[kind]
    mat_s = num_edges * _DELTA_MATERIALIZE_COST_S
    repack_s = num_edges * _REPACK_SCAN_COST_S
    d_star = mat_s / max(expected_runs * supersteps * lane, 1e-12)
    threshold = _next_pow2(int(max(1024, min(d_star, 1 << 16))))
    threshold = min(threshold, 1 << 16)
    return DeltaDecision(
        compact_threshold=threshold,
        device_kind=device_kind,
        source="model",
        cells={
            "materialize_s": mat_s,
            "repack_s": repack_s,
            "lane_cost_per_record_per_step_s": lane,
            "d_star": d_star,
        },
    )


def decide_tiers(
    stats: GraphStats,
    overrides: Optional[dict] = None,
    measured: Optional[dict] = None,
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """(F_cap ladder, E_cap ladder) for the frontier engine: pow2 tiers
    from the configured floors up to (n, m), with the growth factor chosen
    per graph so the ladder stays within the tier budget (each tier is one
    compiled executable) — replacing the fixed x4 growth. The E floor is
    raised to cover one mean-degree expansion of the smallest F tier, so
    the first hops of a BFS never straddle two executables.

    With ``measured`` (a prior frontier run's ``roofline_by_tier``), tiers
    whose measured roofline utilization rounds to zero are dropped from
    the MIDDLE of the ladder (floors and the dense top stay): a tier the
    hardware cannot fill is a compile with no win."""
    ov = dict(overrides or {})
    n = max(1, stats.num_vertices)
    m = max(1, stats.num_edges)
    f_min = int(ov.get("f_min") or (1 << 10))
    e_min = int(ov.get("e_min") or (1 << 13))
    max_tiers = int(ov.get("max_tiers") or 8)
    max_growth = int(ov.get("tier_growth") or 16)

    e_floor = max(e_min, _next_pow2(int(f_min * max(stats.mean_degree, 1.0))))
    e_floor = min(e_floor, _next_pow2(m))

    def ladder(lo: int, hi: int) -> Tuple[int, ...]:
        lo = _next_pow2(lo)
        top = hi  # the top tier is the dense fallback, not rounded up
        if lo >= top:
            return (top,)  # floor covers the whole graph: dense only
        growth = 2
        while growth < max_growth:
            count, c = 1, lo
            while c < top:
                c *= growth
                count += 1
            if count <= max_tiers:
                break
            growth *= 2
        tiers, c = [lo], lo
        while c < top:
            c = min(c * growth, top)
            tiers.append(c)
        return tuple(tiers)

    f_sched = ladder(f_min, n)
    e_sched = ladder(e_floor, m)

    if measured:
        by_tier = measured.get("roofline_by_tier") or {}
        dead = {
            int(k) for k, v in by_tier.items()
            if k.isdigit() and (v.get("roofline_utilization") or 0.0) < 1e-4
        }
        if dead:
            kept = tuple(
                t for i, t in enumerate(e_sched)
                if i == 0 or i == len(e_sched) - 1 or t not in dead
            )
            if len(kept) >= 2:
                e_sched = kept
    return f_sched, e_sched


def pick_tier(need: int, schedule: Tuple[int, ...], hi: int) -> int:
    """Smallest scheduled tier >= need (clamped to hi); the top tier is
    the dense fallback so nothing is ever dropped."""
    for t in schedule:
        if t >= need:
            return min(t, hi)
    return hi


# --------------------------------------------------------------------------
# Measured-record persistence (computer.autotune-persist)
# --------------------------------------------------------------------------
#
# decide() accepts a prior run's `measured` record but nothing survived an
# executor lifetime (ROADMAP #2 leftover). The executor now serializes the
# last measured record next to the checkpoint file and loads it back on
# the next run, so achieved-bandwidth calibration carries across process
# restarts the same way checkpoints carry state.
#
# v2 keys records by SHARD COUNT inside one file: a mesh superstep's
# achieved bandwidth aggregates S chips' HBM plus the collective, which is
# NOT the single-device calibration — an 8-chip run writing the same
# record the 1-chip run reads would poison the next single-device
# decide(). Each layout (shard count) now calibrates only itself; v1
# files (one flat record) are read back as the shard_count=1 entry.

_MEASURED_VERSION = 2

_RECORD_FIELDS = (
    "strategy", "pad_ratio", "superstep_ms", "roofline_by_tier",
    # per-shard-layout fields (sharded executor; absent in older records)
    "exchange", "agg", "halo_cap",
)


def _read_measured_records(path: str) -> Optional[dict]:
    """{shard_count(str): record} from a v1 or v2 file; None when missing
    or unreadable."""
    import json
    import os

    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("version") == 1:
        return {"1": {k: payload.get(k) for k in _RECORD_FIELDS}}
    if payload.get("version") == _MEASURED_VERSION:
        records = payload.get("records")
        return records if isinstance(records, dict) else None
    return None


def save_measured(path: str, record: dict, shard_count: int = 1) -> None:
    """Atomically persist one measured record under its shard-count key
    (tmp + rename, like the checkpoint writer), preserving every other
    layout's record in the file. Persistence must never fail a run — any
    I/O error is swallowed (the next run simply decides from the model
    alone)."""
    import json
    import os
    import tempfile

    records = _read_measured_records(path) or {}
    records[str(int(shard_count))] = {
        k: record.get(k) for k in _RECORD_FIELDS
    }
    payload = {"version": _MEASURED_VERSION, "records": records}
    try:
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    except OSError:
        return


def load_measured(path: str, shard_count: int = 1) -> Optional[dict]:
    """Load the persisted measured record for one shard count; None when
    missing, unreadable, from an unknown version, or not carrying the
    calibration fields. v1 files answer only shard_count=1."""
    records = _read_measured_records(path)
    if records is None:
        return None
    rec = records.get(str(int(shard_count)))
    if not isinstance(rec, dict):
        return None
    if not rec.get("superstep_ms") or not rec.get("pad_ratio"):
        return None
    return rec
