"""Multi-process distributed CSR loading — the Hadoop/Spark input-format
analogue over real processes.

The reference scales OLAP input by splitting the edgestore into
backend-native input splits read by separate Hadoop/Spark workers
(reference: hadoop/formats/util/HadoopInputFormat.java:34,
HadoopRecordReader.java:111 deserializing raw edgestore rows per split).
Here the split unit is the STORAGE PARTITION (the same contiguous key
ranges the mesh shards by): N worker PROCESSES each open the shared backend
(remote TCP server or a persistent local directory), run the raw partition
scan (csr._scan_raw — no endpoint validation, since an edge's destination
may live in another worker's partitions), and ship their arrays back via
npz files; the parent merges and validates once (csr.build_csr_from_raw).

Worker entry: `python -m janusgraph_tpu.olap.distributed_load --config ...
--partitions 0,1,2 --out part.npz` (also used directly by tests).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import List, Optional, Sequence

import numpy as np


def _worker_main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True, help="graph config JSON")
    ap.add_argument("--partitions", required=True, help="comma-separated ids")
    ap.add_argument("--out", required=True, help="output npz path")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")  # loaders never need the TPU

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.olap.csr import _scan_raw

    cfg = json.loads(args.config)
    graph = open_graph(cfg)
    try:
        partitions = [int(p) for p in args.partitions.split(",") if p != ""]
        raw = _scan_raw(graph, None, None, {}, None, partitions)
        np.savez(
            args.out,
            vertex_id_list=np.asarray(raw["vertex_id_list"], dtype=np.int64),
            vertex_labels=np.asarray(raw["vertex_labels"], dtype=np.int64),
            src=raw["src"],
            dst=raw["dst"],
            etype=raw["etype"] if raw["etype"] is not None else np.empty(0, np.int32),
            has_etype=np.asarray([raw["etype"] is not None]),
        )
    finally:
        graph.close()
    return 0


def distributed_load_csr(
    config: dict,
    num_workers: int = 4,
    timeout_s: float = 600.0,
):
    """Load a CSR snapshot with N worker processes over a SHARED backend
    (storage.backend=remote or a persistent local directory — an in-memory
    backend would give each worker an empty private store, which is
    rejected). Returns the merged, validated CSRGraph."""
    backend = config.get("storage.backend", "inmemory")
    if backend not in ("remote", "local"):
        raise ValueError(
            "distributed_load_csr needs a SHARED backend "
            "(storage.backend='remote' or 'local'); "
            f"got {backend!r} whose state is private to each process"
        )
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.ids import IDManager

    # partition count MUST be the cluster's reconciled FIXED value, which can
    # differ from (or be absent in) the caller's dict — the stored global
    # config wins; resolve it the same way the workers will, by opening the
    # graph once (a config.get default here silently loses partitions)
    probe = open_graph(config)
    try:
        pb = probe.idm.partition_bits
    finally:
        probe.close()
    num_partitions = 1 << pb
    num_workers = max(1, min(num_workers, num_partitions))
    assignments: List[List[int]] = [[] for _ in range(num_workers)]
    for p in range(num_partitions):
        assignments[p % num_workers].append(p)

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    cfg_json = json.dumps(config)
    import time as _time

    with tempfile.TemporaryDirectory() as td:
        procs = []
        outs = []
        try:
            for w, parts in enumerate(assignments):
                out = os.path.join(td, f"part{w}.npz")
                outs.append(out)
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-m",
                        "janusgraph_tpu.olap.distributed_load",
                        "--config", cfg_json,
                        "--partitions", ",".join(map(str, parts)),
                        "--out", out,
                    ],
                    cwd=repo_root,
                ))
            # ONE shared deadline (not timeout_s per worker), and a hung or
            # failed worker must not leak the others past this function —
            # they'd keep scanning the shared backend and writing into a
            # deleted tmpdir
            deadline = _time.monotonic() + timeout_s
            failed = []
            for w, proc in enumerate(procs):
                remaining = max(0.1, deadline - _time.monotonic())
                try:
                    rc = proc.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    failed.append(w)
                    continue
                if rc != 0:
                    failed.append(w)
            if failed:
                raise RuntimeError(f"loader workers failed/hung: {failed}")
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for proc in procs:
                if proc.poll() is None:
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass

        raws = []
        for out in outs:
            with np.load(out) as z:
                raws.append({
                    "vertex_id_list": z["vertex_id_list"],
                    "vertex_labels": z["vertex_labels"],
                    "src": z["src"],
                    "dst": z["dst"],
                    "etype": z["etype"] if bool(z["has_etype"][0]) else None,
                    "weights": None,
                    "raw_props": {},
                })

    from janusgraph_tpu.olap.csr import build_csr_from_raw

    idm = IDManager(partition_bits=pb)
    return build_csr_from_raw(idm, raws)


if __name__ == "__main__":
    sys.exit(_worker_main())
