"""VertexProgram SPI — the BSP contract both executors implement.

Capability parity with the reference's vertex-program machinery
(reference: TinkerPop VertexProgram via graphdb/olap/computer/
VertexProgramScanJob.java:82-111 per-vertex execute + FulgoraVertexMemory
double-buffered message slots + message combiners :91-95 + FulgoraMemory
global aggregators), re-designed as an **array-BSP** model: a superstep is

    aggregated[i] = combine({ transform(message(src), w_e) for e=(src, i) })
    state', metrics = apply(state, aggregated, superstep, memory)

with `combine` a segment-reduction monoid and per-vertex state a dict of
dense arrays. This restriction (fixed-width numeric messages with monoid
combiners — SURVEY.md §7 hard part (b)) makes message passing one
segment-reduce / SpMV instead of the reference's NonBlockingHashMapLong
churn; every BASELINE workload fits it.

jit/psum-compatible by construction:
- programs never mutate host state inside the superstep; global aggregators
  flow as `metrics` return values (op, scalar) that the executor reduces at
  the barrier — locally on one chip, with psum/pmin/pmax across a mesh
  (the reference's FulgoraMemory sub-round barrier);
- the previous superstep's reduced aggregators are passed back in as traced
  scalars (`memory_in`), so values like PageRank's dangling-rank mass are
  globally consistent without a second pass;
- `superstep` arrives as a traced scalar: one compiled superstep function
  serves all iterations.

Programs are written against the `xp` array namespace (numpy or jax.numpy),
so one definition runs on the CPU oracle executor and the TPU executor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from types import MappingProxyType
from functools import lru_cache
from typing import Dict, Mapping, Optional, Tuple


class Combiner:
    """Message combination monoids (reference: MessageCombiner)."""

    SUM = "sum"
    MIN = "min"
    MAX = "max"

    IDENTITY = {"sum": 0.0, "min": float("inf"), "max": float("-inf")}


class EdgeTransform:
    """How an edge modifies the message it carries."""

    NONE = "none"
    MUL_WEIGHT = "mul"   # msg * w  (e.g. weighted pagerank)
    ADD_WEIGHT = "add"   # msg + w  (e.g. shortest path)


def check_weighted_transforms(program, csr) -> None:
    """Executors call this at run() entry: a program declaring weight
    transforms (scalar edge_transform OR per-column cols) over a
    weightless CSR would otherwise silently compute as if no transform
    existed (every executor skips transforms when weights are absent) —
    plausible wrong numbers, not an error. E.g. weighted SSSP on a
    weightless snapshot would relax every distance to 0."""
    cols = getattr(program, "edge_transform_cols", None)
    wants_weights = bool(
        cols and any(t != EdgeTransform.NONE for t in cols)
    ) or getattr(
        program, "edge_transform", EdgeTransform.NONE
    ) != EdgeTransform.NONE
    if wants_weights:
        if csr.in_edge_weight is None and csr.out_edge_weight is None:
            raise ValueError(
                f"{type(program).__name__} declares weight-dependent edge "
                "transforms but the CSR snapshot carries no edge weights "
                "— load with a weight key (compute().weight(key) / "
                "load_csr(weight_key=...))"
            )


@lru_cache(maxsize=64)
# graphlint: host -- cached NUMPY constants by design; caching xp arrays would leak tracers
def _col_masks(cols):
    """Per-column {0,1} transform masks, cached as NUMPY — the CPU oracle
    calls the transform once per edge delivery, and caching xp arrays
    would leak tracers out of jit scopes."""
    import numpy as _np

    mul = _np.asarray(
        [1.0 if t == EdgeTransform.MUL_WEIGHT else 0.0 for t in cols],
        dtype=_np.float32,
    )
    add = _np.asarray(
        [1.0 if t == EdgeTransform.ADD_WEIGHT else 0.0 for t in cols],
        dtype=_np.float32,
    )
    return mul, add


# graphlint: traced -- routed into every executor's compiled body (xp=jnp)
def apply_edge_transform(xp, msgs, w, transform, cols=None):
    """Apply a program's in-flight edge transform — THE one shared
    implementation (cpu/tpu-segment/ELL/sharded bodies all route here so
    per-column semantics can never drift between executors).

    `msgs`: (..., k) message columns or (...) scalars, `w`: per-edge
    weights broadcastable to msgs minus its column axis (None = pass).
    With `cols` (= program.edge_transform_cols) set and k-column
    messages, column j rides its own transform: masked as
      msgs * (1 + (w-1)*mul_j) + w*add_j
    (branch-free — compiles to two broadcasts under jit).
    """
    if w is None:
        return msgs
    w = xp.asarray(w)
    if cols is not None:
        # the program contract: with per-column transforms, messages ARE
        # k-column and the LAST axis is the column axis in every layout
        # (flat (E,k), ELL (rows,c,k), oracle row (k,))
        k = msgs.shape[-1]
        if len(cols) != k:
            raise ValueError(
                f"edge_transform_cols has {len(cols)} entries for "
                f"{k}-column messages"
            )
        mul_np, add_np = _col_masks(cols)
        mul = xp.asarray(mul_np, dtype=msgs.dtype)
        add = xp.asarray(add_np, dtype=msgs.dtype)
        shape = (1,) * (msgs.ndim - 1) + (k,)
        wb = w[..., None]
        # where-select, NOT msgs*(1+(w-1)*mul): the algebraic form absorbs
        # |w-1| below float32 eps and mis-scales tiny weights 100%
        return xp.where(
            mul.reshape(shape) > 0, msgs * wb, msgs
        ) + wb * add.reshape(shape)
    if transform == EdgeTransform.MUL_WEIGHT:
        return msgs * (w[..., None] if msgs.ndim > w.ndim else w)
    if transform == EdgeTransform.ADD_WEIGHT:
        return msgs + (w[..., None] if msgs.ndim > w.ndim else w)
    return msgs


@dataclass(frozen=True)
class EdgeChannel:
    """A typed edge view for one message round (reference: TinkerPop
    MessageScope.Local carrying a per-step traversal like __.out('knows'),
    compiled to reversed slice queries at VertexProgramScanJob.java:114-135).

    direction: traverser movement along the edge —
        "out"  src -> dst  (aggregate at dst over in-edges; the default)
        "in"   dst -> src  (aggregate at src over out-edges)
        "both" both orientations
    labels: edge type ids to include (None = all). Requires the CSR to carry
        per-edge type arrays (in_edge_type/out_edge_type).
    """

    direction: str = "out"
    labels: Optional[Tuple[int, ...]] = None


@dataclass
class Memory:
    """Host-side view of the global aggregators, updated at each superstep
    barrier from the reduced metrics (reference: FulgoraMemory.java:45)."""

    values: Dict[str, float] = field(default_factory=dict)
    superstep: int = 0

    def get(self, key: str, default: float = 0.0) -> float:
        return self.values.get(key, default)

    def reduce_in(self, metrics: Dict[str, Tuple[str, float]]) -> None:
        for k, (_op, v) in metrics.items():
            self.values[k] = float(v)
        self.superstep += 1


class VertexProgram:
    """Array-BSP vertex program. Subclasses define the hooks below.

    Class attributes:
      compute_keys    — state entries that write-back persists as properties
      combiner        — Combiner monoid (or override combiner_for per phase)
      edge_transform  — EdgeTransform applied to messages in flight
      edge_transform_cols — per-COLUMN EdgeTransforms for 2-D messages
                        (overrides edge_transform; the substrate for
                        OLAP-side sack: one message column can ride
                        MUL_WEIGHT while the traverser-count column
                        passes untransformed). SUM combiner only — the
                        post-transform identity masking is uniform.
      undirected      — aggregate over both edge orientations
      max_iterations  — hard superstep cap
    """

    compute_keys: Tuple[str, ...] = ()
    combiner: str = Combiner.SUM
    edge_transform: str = EdgeTransform.NONE
    edge_transform_cols: Optional[Tuple[str, ...]] = None
    undirected: bool = False
    max_iterations: int = 100

    #: named typed edge views; programs with per-superstep edge scopes
    #: (the TraversalVertexProgram analogue) SHADOW this with their own dict
    #: and pick one per superstep via channel_for (the immutable default
    #: cannot be mutated in place, so per-class declarations can't leak
    #: across programs)
    edge_channels: Mapping[str, EdgeChannel] = MappingProxyType({})

    def combiner_for(self, superstep: int) -> str:
        """Monoid for a given superstep — overridable for phase-alternating
        programs (e.g. peer pressure's count-then-resolve phases)."""
        return self.combiner

    def channel_for(self, superstep: int) -> Optional[str]:
        """Edge channel for a given superstep. None = the program's default
        edge view (in-CSR, or the symmetric closure when `undirected`)."""
        return None

    def setup(self, graph, xp) -> Tuple[Dict[str, object], Dict[str, Tuple[str, object]]]:
        """Return (initial state, initial metrics). Metrics are (op, scalar)
        pairs reduced across shards before superstep 0 reads them."""
        raise NotImplementedError

    def message(self, state: Dict[str, object], superstep, graph, xp):
        """Per-vertex outgoing message array (n,) or (n, k)."""
        raise NotImplementedError

    def apply(
        self,
        state: Dict[str, object],
        aggregated,
        superstep,
        memory_in: Dict[str, object],
        graph,
        xp,
    ) -> Tuple[Dict[str, object], Dict[str, Tuple[str, object]]]:
        """Fold aggregated messages into new state; emit metrics."""
        raise NotImplementedError

    def terminate(self, memory: Memory) -> bool:
        raise NotImplementedError

    def terminate_device(self, values: Dict[str, object], steps_done, xp):
        """Traceable termination predicate for the fused on-device run loop
        (the whole BSP iteration compiles into ONE lax.while_loop dispatch;
        host-loop executors use `terminate` instead). `values` are the
        barrier-reduced aggregators, `steps_done` a traced step count.
        Default: rely on the loop's max_iterations bound only."""
        return xp.asarray(False)

    #: parameters consumed only by setup() (host-side initial state), not
    #: baked into the traced superstep — excluded from cache_key so varying
    #: them (e.g. BFS seeds) reuses the compiled executable
    setup_only_params: Tuple[str, ...] = ()

    def cache_key(self) -> Tuple:
        """Identity of this program's compiled computation (parameters that
        are baked into the traced superstep)."""
        return (
            type(self).__module__,
            type(self).__qualname__,
            tuple(sorted(
                (k, v) for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, tuple))
                and k not in self.setup_only_params
            )),
        )

    def fused_eligible(self) -> bool:
        """Whether run() may compile the whole iteration into one on-device
        while_loop: requires a constant combiner monoid, a constant edge
        channel, AND an overridden terminate_device (the default never stops
        early, which would change semantics for programs relying on host
        terminate())."""
        return (
            type(self).combiner_for is VertexProgram.combiner_for
            and type(self).channel_for is VertexProgram.channel_for
            and type(self).terminate_device is not VertexProgram.terminate_device
        )
