"""Fused dense-feature kernels: SDDMM–SpMM supersteps over the packed formats.

The scalar-message tier (olap/kernels.py) aggregates an (n,) value per
vertex; this module lifts the same superstep to **[n, d] feature blocks** —
the FusedMM observation (PAPERS.md, arxiv 2011.06391) that one fused
gather -> elementwise/semiring multiply -> aggregate -> dense-transform
kernel shape covers graph-embedding training and GNN message passing.
Three message modes:

  copy      message = source feature row (plain SpMM over the pack)
  weighted  message = w_e * source row (rides the existing MUL_WEIGHT path)
  sddmm     message = <h_src, h_dst> * h_src — the per-edge coefficient is
            a sampled dense–dense matmul over the sparsity pattern
            (dot-attention), fused into the same gather pass

plus an optional post-aggregate **dense transform** (matmul + bias +
nonlinearity) — the op that actually feeds the MXU on TPU.

Bitwise contract (inherited from PR 6): every reduction that feeds vertex
state goes through the fixed adjacent-pair tree (`tree_reduce`), including
the SDDMM dot (`tree_dot`) and the dense matmul's contraction axis
(`tree_matmul`). All entry points are xp-generic (jnp or numpy), so the
CPU executor replays the identical arithmetic — device and oracle results
are bit-for-bit equal on both the ELL and hybrid formats, and ELL vs
hybrid stay bitwise-equal exactly as the scalar tier does. Feature dims
are padded to power-of-two lane tiers (`FEATURE_TIERS`) so the tree-dot
width is always a complete tree (graphlint JG304 enforces pow2 padded
dims); padded columns hold zeros and stay zero through every mode.

`tree_matmul` trades the backend's native dot (MXU) for the deterministic
tree contraction; `native=True` (computer.features-native-matmul) switches
to ``xp.matmul`` for peak MXU throughput at the cost of the cross-backend
bitwise guarantee.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from janusgraph_tpu.olap.kernels import (
    ELLPack,
    HybridPack,
    _is_jax,
    _next_pow2,
    _segment_combine,
    flat_take,
    fp_fence,
    tree_reduce,
)
from janusgraph_tpu.olap.vertex_program import Combiner

#: power-of-two lane-width tiers the feature dimension pads to — the
#: feature-axis analogue of the frontier E_cap ladder. 8 is the smallest
#: tree worth fusing; 512 covers every shipped program, and larger dims
#: fall through to the next power of two.
FEATURE_TIERS = (8, 16, 32, 64, 128, 256, 512)


def pick_feature_tier(d: int, forced: int = 0) -> int:
    """Smallest lane tier >= d (next pow2 above the ladder). ``forced``
    (computer.features-dim-tier) pins the tier; it must be a power of two
    and must not truncate the logical dim."""
    d = int(d)
    if d < 1:
        raise ValueError(f"feature_dim must be >= 1 (got {d})")
    if forced:
        forced = int(forced)
        if forced & (forced - 1) or forced < d:
            raise ValueError(
                f"features dim tier {forced} must be a power of two >= the "
                f"logical feature dim {d}"
            )
        return forced
    for t in FEATURE_TIERS:
        if t >= d:
            return t
    return _next_pow2(d)


def pad_features(h: np.ndarray, d_pad: int) -> np.ndarray:
    """Host-side zero-pad of an (n, d) float block to the (n, d_pad) lane
    tier. Padded columns are zero and every kernel mode preserves that."""
    h = np.asarray(h, dtype=np.float32)
    if h.ndim != 2:
        raise ValueError(f"feature block must be 2-D (got shape {h.shape})")
    n, d = h.shape
    if d == d_pad:
        return h
    if d > d_pad:
        raise ValueError(f"feature dim {d} exceeds padded tier {d_pad}")
    out = np.zeros((n, d_pad), dtype=np.float32)
    out[:, :d] = h
    return out


# graphlint: traced -- the SDDMM dot of every compiled dense superstep
def tree_dot(xp, a, b):
    """Row-wise dot product over the LAST axis (width must be a pow2 lane
    tier) through the fixed adjacent-pair tree — the feature-axis twin of
    `tree_reduce`, so the SDDMM coefficient is bitwise-identical however
    the slots were laid out (ELL row, hybrid torso, tail chunk). The
    product is fenced so the backend can't contract it into the first
    tree level as a bit-changing fused multiply-add."""
    prod = fp_fence(xp, a * b)
    flat = prod.reshape((-1, prod.shape[-1]))
    return tree_reduce(xp, flat, Combiner.SUM).reshape(prod.shape[:-1])


#: materialized (rows, k, j) product budget per matmul block — keeps the
#: tree contraction's intermediate in cache/VMEM-sized chunks
_MM_BLOCK_BYTES = 1 << 23


# graphlint: traced -- the dense-transform contraction of compiled supersteps
def tree_matmul(xp, h, w, native: bool = False):
    """(n, k) @ (k, j) with the contraction folded through the fixed
    adjacent-pair tree over k (k must be a pow2 lane tier). Row-chunked so
    the materialized (chunk, k, j) product stays ~_MM_BLOCK_BYTES; chunking
    never changes bits (rows reduce independently). ``native=True`` uses
    the backend dot instead — the MXU path, outside the bitwise contract."""
    if native:
        return xp.matmul(h, w)
    n, k = h.shape
    j = w.shape[1]
    if k & (k - 1):
        raise ValueError(f"tree_matmul contraction width {k} is not pow2")

    def block(hb):
        return tree_reduce(
            xp, fp_fence(xp, hb[:, :, None] * w[None, :, :]), Combiner.SUM
        )

    rows = max(1, _MM_BLOCK_BYTES // max(1, 4 * k * j))
    rows = 1 << (rows.bit_length() - 1)
    if n <= rows:
        return block(h)
    nb = -(-n // rows)
    pad = nb * rows - n
    if pad:
        h = xp.concatenate(
            [h, xp.zeros((pad, k), dtype=h.dtype)], axis=0
        )
    blocks = h.reshape(nb, rows, k)
    if _is_jax(xp):
        import jax

        out = jax.lax.map(block, blocks)
    else:
        out = xp.stack([block(b) for b in blocks])
    return out.reshape(nb * rows, j)[:n]


_ACTIVATIONS = ("identity", "relu", "tanh")


# graphlint: traced -- post-aggregate dense layer of compiled supersteps
def dense_transform(xp, h, w, b=None, activation: str = "identity",
                    native: bool = False):
    """The post-aggregate dense layer: ``act(h @ w + b)``. relu/identity
    are exact elementwise ops (inside the bitwise contract); tanh is
    backend-libm and documented as outside it."""
    if activation not in _ACTIVATIONS:
        raise ValueError(f"unknown activation {activation!r}")
    out = tree_matmul(xp, h, w, native=native)
    if b is not None:
        out = out + b
    if activation == "relu":
        out = xp.maximum(out, 0.0)
    elif activation == "tanh":
        out = xp.tanh(out)
    return out


# --------------------------------------------------------------------------
# SDDMM row-destination indices
# --------------------------------------------------------------------------
#
# Every slot in a pack row shares one destination vertex, so the SDDMM
# coefficient needs one dst index per ROW (per chunk row in the hybrid
# tail). The builders construct a shadow pack from the same (dst, dst)
# edge list — bucketing depends only on destination degrees, so the shadow
# layout is row-for-row identical to the real pack — and keep column 0 of
# each index matrix: the destination id (the sentinel for all-padding
# rows, whose gathered features read the zero identity).


def ell_row_dsts(
    src: np.ndarray, dst: np.ndarray, num_vertices: int,
    max_capacity: int = 1 << 14,
) -> List[np.ndarray]:
    """Per-bucket (rows,) destination-index vectors aligned with
    ``ELLPack(src, dst, ..., max_capacity)``'s bucket layout."""
    dst = np.asarray(dst, dtype=np.int64)
    shadow = ELLPack(dst, dst, None, num_vertices, max_capacity=max_capacity)
    return [np.ascontiguousarray(b[0][:, 0]) for b in shadow.buckets]


def hybrid_row_dsts(
    src: np.ndarray, dst: np.ndarray, num_vertices: int,
    hub_cutoff: int = 64, tail_chunk: int = 256,
    max_capacity: int = 1 << 14,
) -> dict:
    """{"torso": [...], "tail": [...]} destination-index vectors aligned
    with the equivalent ``HybridPack``'s torso buckets and tail chunks."""
    dst = np.asarray(dst, dtype=np.int64)
    shadow = HybridPack(
        dst, dst, None, num_vertices,
        hub_cutoff=hub_cutoff, tail_chunk=tail_chunk,
        max_capacity=max_capacity,
    )
    return {
        "torso": [np.ascontiguousarray(b["idx"][:, 0]) for b in shadow.torso],
        "tail": [np.ascontiguousarray(b["idx"][:, 0]) for b in shadow.tail],
    }


# --------------------------------------------------------------------------
# Fused SDDMM–SpMM aggregation
# --------------------------------------------------------------------------


def _check_sddmm(op: str, msgs) -> None:
    if op != Combiner.SUM:
        raise ValueError(
            f"sddmm aggregation is SUM-only (dot-attention coefficients "
            f"have no {op} semantics)"
        )
    d = msgs.shape[-1]
    if msgs.ndim != 2 or d & (d - 1):
        raise ValueError(
            f"sddmm needs (n, d) features with a pow2 lane-tier d "
            f"(got shape {tuple(msgs.shape)})"
        )


# graphlint: traced -- the ELL SDDMM body of compiled dense supersteps
def sddmm_ell_aggregate(xp, pack, row_dsts, msgs, op: str = Combiner.SUM):
    """Fused SDDMM+SpMM over an ELLPack (or view): for each in-edge,
    coefficient = <h_src, h_dst> (tree dot), message = coefficient * h_src,
    summed per destination through the shared reduction tree.

    ``row_dsts``: per-bucket (rows,) destination indices (ell_row_dsts).
    Sentinel slots gather the zero identity row, so their coefficient and
    message are exactly zero — the same leaves the hybrid path produces."""
    _check_sddmm(op, msgs)
    if len(row_dsts) != len(pack.buckets):
        raise ValueError(
            f"sddmm row-dst count {len(row_dsts)} != bucket count "
            f"{len(pack.buckets)} (pack drift)"
        )
    identity = Combiner.IDENTITY[op]
    pad_shape = (1,) + tuple(msgs.shape[1:])
    msgs_ext = xp.concatenate(
        [msgs, xp.full(pad_shape, identity, dtype=msgs.dtype)], axis=0
    )
    parts = []
    for (idx, _w, _valid, rowseg, num_slots), rdst in zip(
        pack.buckets, row_dsts
    ):
        m = flat_take(xp, msgs_ext, idx)           # (rows, c, d)
        dstf = flat_take(xp, msgs_ext, rdst)       # (rows, d)
        alpha = tree_dot(xp, m, dstf[:, None, :])  # (rows, c)
        r = tree_reduce(xp, fp_fence(xp, m * alpha[:, :, None]), op)
        if rowseg is not None:
            # split supernode rows share one destination, so each row's
            # alpha used the right dst; the fold just sums row partials
            r = _segment_combine(xp, op, r, rowseg, num_slots)
        parts.append(r)
    if not parts:
        return xp.full(msgs.shape, identity, dtype=msgs.dtype)
    stacked = xp.concatenate(parts, axis=0)
    return stacked[pack.unpermute]


# graphlint: traced -- the hybrid SDDMM body of compiled dense supersteps
def sddmm_hybrid_aggregate(xp, pack, row_dsts, msgs, op: str = Combiner.SUM):
    """Fused SDDMM+SpMM over a HybridPack (or view) — bitwise-identical to
    `sddmm_ell_aggregate` by the same aligned-subtree argument as the
    scalar tier: per-slot coefficients are elementwise, so the leaves of
    every row's reduction tree carry identical bits in both layouts."""
    _check_sddmm(op, msgs)
    if len(row_dsts["torso"]) != len(pack.torso_meta) or len(
        row_dsts["tail"]
    ) != len(pack.tail_meta):
        raise ValueError(
            f"sddmm row-dst counts ({len(row_dsts['torso'])}/"
            f"{len(row_dsts['tail'])}) != hybrid metadata "
            f"({len(pack.torso_meta)}/{len(pack.tail_meta)}) (pack drift)"
        )
    identity = Combiner.IDENTITY[op]
    pad_shape = (1,) + tuple(msgs.shape[1:])
    msgs_ext = xp.concatenate(
        [msgs, xp.full(pad_shape, identity, dtype=msgs.dtype)], axis=0
    )
    parts = []
    for entry, (d, cap), rdst in zip(
        pack.torso, pack.torso_meta, row_dsts["torso"]
    ):
        m = flat_take(xp, msgs_ext, entry["idx"])   # (rows, d_deg, d)
        dstf = flat_take(xp, msgs_ext, rdst)
        alpha = tree_dot(xp, m, dstf[:, None, :])
        m = fp_fence(xp, m * alpha[:, :, None])
        if cap > d:
            fill = xp.full(
                (m.shape[0], cap - d) + tuple(m.shape[2:]), identity,
                dtype=m.dtype,
            )
            m = xp.concatenate([m, fill], axis=1)
        parts.append(tree_reduce(xp, m, op))

    if pack.num_zero:
        parts.append(
            xp.full(
                (pack.num_zero,) + tuple(msgs.shape[1:]), identity,
                dtype=msgs.dtype,
            )
        )

    for entry, (cap, ppr, rows, num_slots), rdst in zip(
        pack.tail, pack.tail_meta, row_dsts["tail"]
    ):
        m = flat_take(xp, msgs_ext, entry["idx"])   # (chunks, T, d)
        dstf = flat_take(xp, msgs_ext, rdst)        # (chunks, d)
        alpha = tree_dot(xp, m, dstf[:, None, :])
        part = tree_reduce(xp, fp_fence(xp, m * alpha[:, :, None]), op)
        tab_shape = (rows * ppr,) + tuple(part.shape[1:])
        if _is_jax(xp):
            table = xp.full(tab_shape, identity, dtype=part.dtype)
            table = table.at[entry["slot"]].set(part)
        else:
            table = xp.full(tab_shape, identity, dtype=part.dtype)
            table[entry["slot"]] = part
        table = table.reshape((rows, ppr) + tuple(part.shape[1:]))
        r = tree_reduce(xp, table, op)
        rowseg = entry.get("rowseg")
        if rowseg is not None:
            r = _segment_combine(xp, op, r, rowseg, num_slots)
        parts.append(r)

    if not parts:
        return xp.full(msgs.shape, identity, dtype=msgs.dtype)
    stacked = xp.concatenate(parts, axis=0)
    return stacked[pack.unpermute]


# graphlint: traced -- the flat-gather SDDMM fallback (segment strategy)
def sddmm_segment_aggregate(xp, msgs, src_idx, dst_idx, num_vertices: int):
    """Flat SDDMM+SpMM: per-edge coefficient from the edge list, then a
    segment sum. The fallback when neither packed layout fits the budget;
    outside the pack-vs-pack bitwise contract (scatter-add ordering)."""
    _check_sddmm(Combiner.SUM, msgs)
    hs = msgs[src_idx]
    hd = msgs[dst_idx]
    alpha = tree_dot(xp, hs, hd)
    vals = fp_fence(xp, hs * alpha[:, None])
    if _is_jax(xp):
        import jax

        return jax.ops.segment_sum(vals, dst_idx, num_segments=num_vertices)
    return _segment_sum_host(vals, dst_idx, num_vertices)


# graphlint: host -- numpy-only branch, unreachable from traced code
def _segment_sum_host(vals, dst_idx, num_vertices: int):
    out = np.zeros((num_vertices, vals.shape[1]), dtype=vals.dtype)
    np.add.at(out, np.asarray(dst_idx), np.asarray(vals))
    return out


def sddmm_flops(num_edges: int, d_pad: int) -> float:
    """MXU-attributable flops of one SDDMM pass: a length-d dot (2d ops)
    plus the coefficient multiply (d ops) per edge."""
    return 3.0 * float(num_edges) * float(d_pad)


def matmul_flops(n: int, d_in: int, d_out: int) -> float:
    """MXU-attributable flops of one (n, d_in) @ (d_in, d_out) layer."""
    return 2.0 * float(n) * float(d_in) * float(d_out)
