"""DenseVertexProgram — the [N, d] feature-block vertex-program contract.

Extends the array-BSP `VertexProgram` SPI (olap/vertex_program.py) with the
dense-feature tier's vocabulary:

  feature_keys    state entries that are (n, d_pad) feature blocks
  feature_dim     the LOGICAL feature width d
  d_pad           d padded to a power-of-two lane tier (FEATURE_TIERS);
                  padded columns are zero and every kernel mode keeps them
                  zero, so write-back/bitwise checks can slice [:, :d]
  message_mode    copy | weighted | sddmm — how an edge transforms the
                  source's feature row in flight (weighted rides the
                  existing MUL_WEIGHT machinery; sddmm computes a per-edge
                  dot-attention coefficient fused into the gather)
  dense_layer()   the post-aggregate matmul+bias+activation helper
                  (features/kernels.dense_transform) — the MXU op
  matmul_flops()  per-superstep MXU-attributable flops, consumed by the
                  executors' `mxu_utilization` run-record fields

Combiner semantics lift unchanged to the feature axes: SUM/MIN/MAX apply
elementwise over the d columns (the scalar tier's (n, k)-message support
already provides this for copy/weighted; sddmm is SUM-only).

Programs stay xp-generic and keep every state-feeding reduction on the
fixed-tree kernels, so one definition runs bitwise-identically on the CPU
oracle and the device executors.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from janusgraph_tpu.olap.features.kernels import (
    dense_transform,
    matmul_flops,
    pad_features,
    pick_feature_tier,
    sddmm_flops,
)
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    VertexProgram,
)


class MessageMode:
    """How an edge transforms the source's feature row in flight."""

    COPY = "copy"
    WEIGHTED = "weighted"
    SDDMM = "sddmm"

    ALL = (COPY, WEIGHTED, SDDMM)


class DenseVertexProgram(VertexProgram):
    """Base class for dense-feature vertex programs. Subclasses set
    `feature_keys`, pick a `message_mode`, and implement the usual
    setup/message/apply hooks over (n, d_pad) blocks."""

    feature_keys: Tuple[str, ...] = ()
    message_mode: str = MessageMode.COPY
    combiner = Combiner.SUM

    def __init__(
        self,
        feature_dim: int,
        dim_tier: int = 0,
        native_matmul: bool = False,
    ):
        self.feature_dim = int(feature_dim)
        self.dim_tier = int(dim_tier or 0)
        self.native_matmul = bool(native_matmul)
        if self.message_mode not in MessageMode.ALL:
            raise ValueError(f"unknown message_mode {self.message_mode!r}")
        if self.message_mode == MessageMode.WEIGHTED:
            # ride the scalar tier's in-flight weight machinery; executors
            # already guard weightless CSRs (check_weighted_transforms)
            self.edge_transform = EdgeTransform.MUL_WEIGHT
        if self.message_mode == MessageMode.SDDMM and (
            self.combiner != Combiner.SUM
        ):
            raise ValueError("sddmm programs must use the SUM combiner")
        self.d_pad = pick_feature_tier(self.feature_dim, self.dim_tier)

    @property
    def sharded_compatible(self) -> bool:
        """Whether the mesh executor can run this program: the blocked /
        a2a halo exchanges ship source-side rows only, and sddmm needs
        both endpoints' features inside one kernel — so attention
        programs stay single-device (GraphComputer routing and
        ShardedExecutor.run both consult this)."""
        return self.message_mode != MessageMode.SDDMM

    # ------------------------------------------------------- configuration
    def set_dim_tier(self, tier: int) -> None:
        """Apply computer.features-dim-tier: re-pick the padded lane tier
        (run_on calls this before setup, so state shapes see it)."""
        self.dim_tier = int(tier or 0)
        self.d_pad = pick_feature_tier(self.feature_dim, self.dim_tier)

    def set_native_matmul(self, native: bool) -> None:
        """Apply computer.features-native-matmul: backend dot (MXU) instead
        of the deterministic tree contraction."""
        self.native_matmul = bool(native)

    # ------------------------------------------------------------- helpers
    def pad_block(self, h: np.ndarray) -> np.ndarray:
        """Zero-pad an (n, feature_dim) host block to (n, d_pad)."""
        return pad_features(h, self.d_pad)

    def dense_layer(self, xp, h, w, b=None, activation: str = "identity"):
        """The post-aggregate dense transform (matmul + bias + activation);
        honors the program's native-matmul setting."""
        return dense_transform(
            xp, h, w, b, activation, native=self.native_matmul
        )

    # ---------------------------------------------------------------- cost
    def matmul_flops(self, num_vertices: int, num_edges: int) -> float:
        """Per-superstep MXU-attributable flops (dense layers + sddmm
        dots). Subclasses with dense layers should extend this; the base
        accounts the sddmm coefficient pass only."""
        if self.message_mode == MessageMode.SDDMM:
            return sddmm_flops(num_edges, self.d_pad)
        return 0.0

    @staticmethod
    def layer_flops(n: int, d_in: int, d_out: int) -> float:
        return matmul_flops(n, d_in, d_out)
