from janusgraph_tpu.olap.features.dense_program import (  # noqa: F401
    DenseVertexProgram,
    MessageMode,
)
from janusgraph_tpu.olap.features.kernels import (  # noqa: F401
    FEATURE_TIERS,
    dense_transform,
    ell_row_dsts,
    hybrid_row_dsts,
    pad_features,
    pick_feature_tier,
    sddmm_ell_aggregate,
    sddmm_hybrid_aggregate,
    sddmm_segment_aggregate,
    tree_dot,
    tree_matmul,
)
