"""OLAP maintenance jobs: index repair/removal and ghost-vertex purging.

Capability parity with the reference's scan-framework jobs
(reference: graphdb/olap/job/IndexRepairJob.java:48 — REINDEX re-derives
index entries for every vertex; IndexRemoveJob.java — deletes an index's
stored data; GhostVertexRemover.java:44 — purges half-deleted vertices;
all run over StandardScanner, or Hadoop MR at cluster scale via
MapReduceIndexManagement.java:276).

TPU-build shape: jobs are batch-oriented ScanJobs over the edgestore; rows
arrive as raw relation cells, decoded with the same EdgeSerializer the OLTP
path uses, and mutations flow through a backend transaction (composite) or
an IndexProvider.restore call (mixed)."""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.schema import IndexDefinition, PropertyKey
from janusgraph_tpu.storage.kcvs import KeyRangeQuery, KeySliceQuery, SliceQuery
from janusgraph_tpu.storage.scan import ScanJob, ScanMetrics, StandardScanner


def _codec_schema(graph):
    def lookup(type_id: int):
        info = graph.system_types.type_info(type_id)
        if info is not None:
            return info
        el = graph.schema_cache.get_by_id(type_id)
        if el is None:
            raise KeyError(type_id)
        return el.type_info()

    return lookup


class _VertexRowJob(ScanJob):
    """Base for jobs iterating live vertex rows: declares the EXISTS slice as
    the primary query, skips schema vertices and ghosts (reference:
    VertexJobConverter.java:123-143 ghost check + conversion)."""

    def __init__(self, graph):
        self.graph = graph
        self.es = graph.edge_serializer
        self.st = graph.system_types
        self.idm = graph.idm
        self.schema = _codec_schema(graph)
        self.exists_q = self.es.get_type_slice(self.st.EXISTS, False)
        self.label_q = self.es.get_type_slice(
            self.st.VERTEX_LABEL_EDGE, True, Direction.OUT
        )

    def vertex_label(self, entries) -> Optional[str]:
        for e in entries:
            rc = self.es.parse_relation(e, self.schema)
            el = self.graph.schema_cache.get_by_id(rc.other_vertex_id)
            if el is not None:
                return el.name
        return "vertex"


class IndexRepairJob(_VertexRowJob):
    """Re-derive one index's entries for every live vertex (reference:
    graphdb/olap/job/IndexRepairJob.java:48). Composite rows are written
    through a backend tx; mixed documents are batched and pushed with
    IndexProvider.restore (the reference's reindexElement path)."""

    def __init__(self, graph, index: IndexDefinition):
        super().__init__(graph)
        self.index = index
        self.key_slices: List[Tuple[int, str, SliceQuery]] = []
        for kid in index.key_ids:
            pk = graph.schema_cache.get_by_id(kid)
            if isinstance(pk, PropertyKey):
                self.key_slices.append(
                    (kid, pk.name, self.es.get_type_slice(kid, False))
                )
        self._docs: Dict[str, list] = {}
        self._btx = None

    def get_queries(self) -> List[SliceQuery]:
        qs = [self.exists_q, self.label_q]
        qs.extend(q for _, _, q in self.key_slices)
        return qs

    def setup(self, metrics: ScanMetrics) -> None:
        if not self.index.mixed:
            self._btx = self.graph.backend.begin_transaction()

    def process(self, rows, metrics: ScanMetrics) -> None:
        from janusgraph_tpu.indexing import IndexEntry

        idx = self.index
        for key, by_query in rows:
            vid = self.idm.get_vertex_id(key)
            if self.idm.is_schema_vertex_id(vid):
                continue
            if not by_query.get(self.exists_q):
                metrics.increment("ghost-skipped")
                continue
            if idx.label_constraint is not None:
                label = self.vertex_label(by_query.get(self.label_q, ()))
                if label != idx.label_constraint:
                    continue
            values: Dict[int, list] = {}
            for kid, _name, q in self.key_slices:
                vals = []
                for e in by_query.get(q, ()):
                    rc = self.es.parse_relation(e, self.schema)
                    vals.append(rc.value)
                values[kid] = vals
            if idx.mixed:
                entries = []
                for kid, name, _q in self.key_slices:
                    entries.extend(IndexEntry(name, v) for v in values[kid])
                if entries:
                    self._docs[str(vid)] = entries
                    metrics.increment("documents-added")
            else:
                tup = tuple(
                    values[kid][0] if values[kid] else None
                    for kid in idx.key_ids
                )
                if any(v is None for v in tup):
                    continue
                for row, adds, _dels in self.graph.index_serializer.index_updates(
                    idx, vid, None, tup
                ):
                    if adds:
                        self._btx.mutate_index(row, adds, [])
                        metrics.increment("index-entries-added")
            metrics.add_rows(1)

    def teardown(self, metrics: ScanMetrics) -> None:
        if self.index.mixed:
            if self._docs:
                self.graph.mixed_index_fields(self.index, register=True)
                self.graph.index_providers[self.index.backing].restore(
                    {self.index.name: self._docs}, self.graph._mixed_key_infos
                )
        elif self._btx is not None:
            self._btx.commit()


class IndexRemoveJob:
    """Delete an index's stored data (reference:
    graphdb/olap/job/IndexRemoveJob.java). Composite indexes scan the
    `graphindex` store for the index-id key prefix — not the edgestore — so
    this is a key-range delete, not a ScanJob over vertices. Mixed indexes
    clear the provider's store."""

    def __init__(self, graph, index: IndexDefinition):
        self.graph = graph
        self.index = index

    def run(self) -> ScanMetrics:
        metrics = ScanMetrics()
        idx = self.index
        if idx.mixed:
            provider = self.graph.index_providers[idx.backing]
            # drop only this index's store (the provider may back others)
            if hasattr(provider, "_stores"):
                provider._stores.pop(idx.name, None)
            metrics.increment("stores-cleared")
            return metrics
        btx = self.graph.backend.begin_transaction()
        prefix = struct.pack(">Q", idx.id)
        store = self.graph.backend.indexstore
        if self.graph.backend.manager.features.ordered_scan:
            it = store.get_keys(
                KeyRangeQuery(prefix, prefix + b"\xff" * 17, SliceQuery()),
                btx.store_tx,
            )
        else:
            it = (
                (k, es)
                for k, es in store.get_keys(SliceQuery(), btx.store_tx)
                if k.startswith(prefix)
            )
        for key, entries in it:
            cols = [col for col, _ in entries]
            if cols:
                btx.mutate_index(key, [], cols)
                metrics.increment("index-entries-removed", len(cols))
            metrics.add_rows(1)
        btx.commit()
        return metrics


class GhostVertexRemover(_VertexRowJob):
    """Purge rows of half-deleted vertices: any non-schema row whose EXISTS
    cell is gone but that still has relation cells (reference:
    graphdb/olap/job/GhostVertexRemover.java:44 — ghosts arise from
    concurrent deletion and writes under eventual consistency)."""

    GHOST_REMOVED = "ghost-removed"

    def __init__(self, graph):
        super().__init__(graph)
        self._btx = None
        self.full_row = SliceQuery()

    def get_queries(self) -> List[SliceQuery]:
        return [self.full_row, self.exists_q]

    def setup(self, metrics: ScanMetrics) -> None:
        self._btx = self.graph.backend.begin_transaction()

    def process(self, rows, metrics: ScanMetrics) -> None:
        for key, by_query in rows:
            vid = self.idm.get_vertex_id(key)
            if self.idm.is_schema_vertex_id(vid):
                continue
            if by_query.get(self.exists_q):
                metrics.add_rows(1)
                continue
            cols = [col for col, _ in by_query.get(self.full_row, ())]
            if cols:
                self._btx.mutate_edges(key, [], cols)
                metrics.increment(self.GHOST_REMOVED)
            metrics.add_rows(1)

    def teardown(self, metrics: ScanMetrics) -> None:
        if self._btx is not None:
            self._btx.commit()


def run_scan_job(
    graph,
    job: ScanJob,
    num_workers: int = None,
    batch_size: int = None,
) -> ScanMetrics:
    """Run a ScanJob over the edgestore, partition-parallel (reference:
    Backend.buildEdgeScanJob → StandardScanner; partition ranges =
    IDManager key ranges, the same structure the TPU mesh shards by).
    Worker count and batch size default to the graph's registered config
    (storage.scan-parallelism / storage.scan-batch-size)."""
    cfg = getattr(graph, "config", None)
    if num_workers is None:
        num_workers = cfg.get("storage.scan-parallelism") if cfg else 1
        if not num_workers:  # 0 = one worker per partition
            num_workers = graph.idm.num_partitions
    if batch_size is None:
        batch_size = cfg.get("storage.scan-batch-size") if cfg else 4096
    btx = graph.backend.begin_transaction()
    scanner = StandardScanner(
        graph.backend.edgestore,
        btx.store_tx,
        ordered_scan=graph.backend.manager.features.ordered_scan,
        retries=cfg.get("storage.scan-retries") if cfg else 3,
    )
    ranges = [
        graph.idm.partition_key_range(p)
        for p in range(graph.idm.num_partitions)
    ]
    return scanner.execute(
        job, key_ranges=ranges, num_workers=num_workers, batch_size=batch_size
    )
