"""node2vec-style embedding update as a dense-feature vertex program.

Each superstep is one SGD-flavored embedding sweep: gather neighbor
embedding rows (gather–multiply–accumulate — optionally walk-weighted or
dot-attention scored), mean-normalize into a positive pull, and push away
from the mean of a **negative-sampling table passed as a dense side
input** (the skip-gram negative term, pre-reduced host-side so the traced
superstep consumes one (d_pad,) constant):

    emb' = (1 - decay) * emb + lr * (pos_mean - neg_mean)

Every state-feeding op is elementwise or rides the fixed-tree kernels, so
the update is bitwise-identical across the CPU oracle and device
executors on both packed formats.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from janusgraph_tpu.olap.features.dense_program import (
    DenseVertexProgram,
    MessageMode,
)
from janusgraph_tpu.olap.features.kernels import pad_features
from janusgraph_tpu.olap.kernels import fp_fence
from janusgraph_tpu.olap.vertex_program import Combiner


class EmbeddingUpdateProgram(DenseVertexProgram):
    """Iterative embedding refinement (node2vec/DeepWalk-shaped).

    State: ``emb`` — the (n, d_pad) embedding block. ``mode`` picks the
    gather semantics: "copy" (uniform neighbors), "weighted" (walk
    transition weights from the CSR weight column), or "sddmm"
    (similarity-scored neighbors). ``neg_table`` is the (K, feature_dim)
    negative-sample side input; omitted, it is seeded deterministically."""

    feature_keys = ("emb",)

    def __init__(
        self,
        feature_dim: int = 16,
        lr: float = 0.05,
        decay: float = 0.01,
        negatives: int = 8,
        seed: int = 11,
        max_iterations: int = 5,
        tol: float = 0.0,
        mode: str = MessageMode.COPY,
        neg_table: Optional[np.ndarray] = None,
        dim_tier: int = 0,
        native_matmul: bool = False,
    ):
        self.message_mode = mode
        super().__init__(
            feature_dim, dim_tier=dim_tier, native_matmul=native_matmul
        )
        self.lr = float(lr)
        self.decay = float(decay)
        self.negatives = int(negatives)
        self.seed = int(seed)
        self.max_iterations = int(max_iterations)
        self.tol = float(tol)
        if neg_table is None:
            rng = np.random.default_rng(self.seed)
            neg_table = (
                rng.standard_normal((self.negatives, self.feature_dim)) * 0.1
            )
        neg_table = np.asarray(neg_table, dtype=np.float32)
        if neg_table.shape[1] != self.feature_dim:
            raise ValueError(
                f"neg_table width {neg_table.shape[1]} != feature_dim "
                f"{self.feature_dim}"
            )
        self._neg_table = neg_table
        # the negative term is a constant of the run: pre-reduce the table
        # host-side (f64 mean, f32 result) so both executors consume the
        # exact same (feature_dim,) bits
        self._neg_mean = np.mean(
            neg_table.astype(np.float64), axis=0
        ).astype(np.float32)

    # ----------------------------------------------------------------- BSP
    def setup(self, graph, xp):
        n = graph.num_vertices
        rng = np.random.default_rng(self.seed + 1)
        emb = (
            rng.standard_normal((n, self.feature_dim))
            / np.sqrt(self.feature_dim)
        ).astype(np.float32)
        emb = pad_features(emb, self.d_pad)
        # zero rows for mesh padding (see GCNForwardProgram.setup)
        local = getattr(graph, "local_num_vertices", n)
        if local > n:
            emb = np.vstack(
                [emb, np.zeros((local - n, emb.shape[1]), emb.dtype)]
            )
        return {"emb": xp.asarray(emb)}, {
            "delta": (Combiner.SUM, float("inf")),
        }

    def message(self, state, superstep, graph, xp):
        return state["emb"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        emb = state["emb"]
        indeg = xp.asarray(graph.in_degree, dtype=emb.dtype)
        pos = aggregated / xp.maximum(indeg, 1.0)[:, None]
        neg = xp.asarray(
            pad_features(self._neg_mean[None, :], self.d_pad)[0],
            dtype=emb.dtype,
        )
        # both products are fenced so the final add's bits match the
        # numpy oracle's separately-rounded mul+add (no fused multiply-add)
        keep = fp_fence(xp, (1.0 - self.decay) * emb)
        push = fp_fence(xp, self.lr * (pos - neg[None, :]))
        emb2 = keep + push
        # convergence metric only (backend-ordered reduction, not part of
        # the bitwise state contract); default tol=0.0 never triggers it
        delta = xp.sum(xp.abs(emb2 - emb))
        return {"emb": emb2}, {"delta": (Combiner.SUM, delta)}

    def terminate(self, memory):
        return memory.superstep >= 1 and memory.get("delta", 1.0) < self.tol

    def terminate_device(self, values, steps_done, xp):
        return xp.logical_and(steps_done >= 1, values["delta"] < self.tol)
