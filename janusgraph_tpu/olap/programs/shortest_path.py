"""Single-source shortest path / BSP BFS (BASELINE config #3 workload).

Reference behavior modeled: TinkerPop ShortestPathVertexProgram as run by
FulgoraGraphComputer (special-cased at FulgoraGraphComputer.java:249-253)
and janusgraph-backend-testutils .../olap/ShortestDistanceVertexProgram.java:
min-combined distance relaxation until fixpoint. Unweighted mode is BFS
hop counting; weighted mode adds the edge weight in flight.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    VertexProgram,
)

INF = 1e18


class ShortestPathProgram(VertexProgram):
    """Min-relaxation SSSP / BFS.

    track_paths=True additionally materializes a predecessor array so actual
    paths can be reconstructed on host (reference: TinkerPop
    ShortestPathVertexProgram materializes paths, special-cased at
    FulgoraGraphComputer.java:249-253; the TPU-native form is a predecessor
    index per vertex + host chain-walk, not per-traverser path objects).
    Unweighted only: at superstep t the frontier is exactly {dist == t}, so
    the message is the sender's own (global) index where it is on the
    frontier and +inf elsewhere; MIN-combining yields, at each newly reached
    vertex, the smallest-index frontier neighbor as its predecessor —
    float32-exact (indices < 2^24), no wide encodings needed.
    """

    compute_keys = ("distance",)
    combiner = Combiner.MIN
    setup_only_params = ("seed_index",)

    def __init__(
        self,
        seed_index: int,
        weighted: bool = False,
        undirected: bool = False,
        max_iterations: int = 100,
        track_paths: bool = False,
    ):
        if track_paths and weighted:
            raise ValueError(
                "track_paths requires unweighted BFS (frontier-index "
                "predecessor encoding); for weighted paths run distances "
                "to fixpoint and derive predecessors with "
                "weighted_predecessors(csr, result, seed)"
            )
        self.seed_index = seed_index
        self.weighted = weighted
        self.track_paths = track_paths
        self.edge_transform = (
            EdgeTransform.ADD_WEIGHT if weighted else EdgeTransform.NONE
        )
        self.undirected = undirected
        self.max_iterations = max_iterations
        if track_paths:
            self.compute_keys = ("distance", "predecessor")

    def setup(self, graph, xp):
        idx = xp.arange(graph.local_num_vertices) + graph.global_offset
        dist = xp.where(idx == self.seed_index, 0.0, INF)
        state = {"distance": dist}
        if self.track_paths:
            if graph.num_vertices >= (1 << 24):
                raise ValueError(
                    "track_paths stores vertex indices in float32 state, "
                    "exact only below 2^24 vertices; run distances without "
                    "paths at this scale"
                )
            # seed points at itself; unreached at -1
            state["predecessor"] = xp.where(
                idx == self.seed_index, float(self.seed_index), -1.0
            )
        return state, {"changed": (Combiner.SUM, xp.asarray(1.0))}

    def message(self, state, superstep, graph, xp):
        if self.track_paths:
            idx = xp.arange(graph.local_num_vertices) + graph.global_offset
            on_frontier = state["distance"] == superstep
            return xp.where(on_frontier, idx.astype(state["distance"].dtype), INF)
        if self.weighted:
            return state["distance"]
        return state["distance"] + 1.0

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        old = state["distance"]
        if self.track_paths:
            newly = (old >= INF) & (aggregated < INF)
            dist = xp.where(newly, superstep + 1.0, old)
            pred = xp.where(newly, aggregated, state["predecessor"])
            changed = xp.sum(xp.where(newly, 1.0, 0.0))
            return (
                {"distance": dist, "predecessor": pred},
                {"changed": (Combiner.SUM, changed)},
            )
        new = xp.minimum(old, aggregated)
        changed = xp.sum(xp.where(new < old, 1.0, 0.0))
        return {"distance": new}, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        return memory.get("changed", 1.0) == 0.0

    def terminate_device(self, values, steps_done, xp):
        return values["changed"] == 0.0


def reconstruct_path(result, target_index: int):
    """Walk the predecessor chain host-side: [seed, ..., target], or None if
    the target was never reached. `result` is a run() output of a
    track_paths=True program."""
    import numpy as np

    pred = np.asarray(result["predecessor"]).astype(np.int64)
    dist = np.asarray(result["distance"])
    if target_index >= len(pred) or dist[target_index] >= INF:
        return None
    path = [int(target_index)]
    v = int(target_index)
    for _ in range(len(pred)):
        p = int(pred[v])
        if p < 0:
            return None
        if p == v:  # seed reached
            return list(reversed(path))
        path.append(p)
        v = p
    return None  # cycle guard — malformed predecessor array


def weighted_predecessors(csr, result, seed_index: int):
    """Predecessor array for a WEIGHTED run, derived host-side from the
    converged distances in one vectorized O(E) pass: v's predecessor is
    any in-neighbor u with dist[u] + w(u,v) == dist[v] (ties broken by
    first slot). The device program cannot carry predecessors in weighted
    mode (its frontier-index encoding is hop-count-based), but at a
    FIXPOINT the relaxation equation identifies them exactly — so paths
    come from distances, not from extra device state. Returns an array
    shaped like the unweighted tracker: pred[seed] = seed, -1 where
    unreached, ready for reconstruct_path (reference capability:
    TinkerPop ShortestPathVertexProgram with the distance modulator).
    Float tolerance: weights accumulate in f32 on device, so the
    equality check allows 1e-4 relative slack."""
    import numpy as np

    dist = np.asarray(result["distance"], dtype=np.float64)
    n = csr.num_vertices
    if csr.in_edge_weight is None:
        raise ValueError(
            "weighted_predecessors needs a weight-materialized CSR "
            "(load_csr(..., weight_key=...))"
        )
    src = csr.in_src.astype(np.int64)
    w = csr.in_edge_weight.astype(np.float64)
    dstv = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(csr.in_indptr)
    )
    cand = dist[src] + w
    ok = np.abs(cand - dist[dstv]) <= 1e-4 * np.maximum(
        1.0, np.abs(dist[dstv])
    )
    ok &= dist[dstv] < INF
    ok &= src != dstv  # a self-loop must never be its own predecessor
    pred = np.full(n, -1, dtype=np.int64)
    pred[seed_index] = seed_index
    # Phase 1 — STRICT edges (dist[u] < dist[v]): any satisfying slot is
    # a valid predecessor; chains strictly decrease in distance, so no
    # cycles are possible.
    strict = ok & (dist[src] < dist[dstv])
    s_slots = np.nonzero(strict)[0][::-1]  # first slot wins
    mask = pred[dstv[s_slots]] == -1
    # the seed's pred stays itself even if a strict in-edge matches
    mask &= dstv[s_slots] != seed_index
    pred[dstv[s_slots][mask]] = src[s_slots][mask]
    # Phase 2 — zero-weight (sub-tolerance) equality edges: dist[u] ==
    # dist[v]. Naive slot-order picks can form u<->v cycles; instead BFS
    # from the already-assigned set through these edges, so every
    # assignment points strictly toward the seed along a real shortest
    # path (the entering vertex of each equal-distance class was
    # assigned in phase 1, or IS the seed).
    eq_slots = np.nonzero(ok & (dist[src] >= dist[dstv]))[0]
    if len(eq_slots):
        from collections import defaultdict, deque

        out_eq = defaultdict(list)  # u -> [v] over equality edges
        for i in eq_slots:
            out_eq[int(src[i])].append(int(dstv[i]))
        # graphlint: disable=JG206 -- BFS work queue: each vertex enqueues at most once (pred guard), so the bound is the vertex count
        queue = deque(int(v) for v in np.nonzero(pred != -1)[0])
        while queue:
            u = queue.popleft()
            for v in out_eq.get(u, ()):
                if pred[v] == -1:
                    pred[v] = u
                    queue.append(v)
    return pred
