"""Single-source shortest path / BSP BFS (BASELINE config #3 workload).

Reference behavior modeled: TinkerPop ShortestPathVertexProgram as run by
FulgoraGraphComputer (special-cased at FulgoraGraphComputer.java:249-253)
and janusgraph-backend-testutils .../olap/ShortestDistanceVertexProgram.java:
min-combined distance relaxation until fixpoint. Unweighted mode is BFS
hop counting; weighted mode adds the edge weight in flight.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    VertexProgram,
)

INF = 1e18


class ShortestPathProgram(VertexProgram):
    """Min-relaxation SSSP / BFS.

    track_paths=True additionally materializes a predecessor array so actual
    paths can be reconstructed on host (reference: TinkerPop
    ShortestPathVertexProgram materializes paths, special-cased at
    FulgoraGraphComputer.java:249-253; the TPU-native form is a predecessor
    index per vertex + host chain-walk, not per-traverser path objects).
    Unweighted only: at superstep t the frontier is exactly {dist == t}, so
    the message is the sender's own (global) index where it is on the
    frontier and +inf elsewhere; MIN-combining yields, at each newly reached
    vertex, the smallest-index frontier neighbor as its predecessor —
    float32-exact (indices < 2^24), no wide encodings needed.
    """

    compute_keys = ("distance",)
    combiner = Combiner.MIN
    setup_only_params = ("seed_index",)

    def __init__(
        self,
        seed_index: int,
        weighted: bool = False,
        undirected: bool = False,
        max_iterations: int = 100,
        track_paths: bool = False,
    ):
        if track_paths and weighted:
            raise ValueError(
                "track_paths requires unweighted BFS (frontier-index "
                "predecessor encoding); run weighted distances without paths"
            )
        self.seed_index = seed_index
        self.weighted = weighted
        self.track_paths = track_paths
        self.edge_transform = (
            EdgeTransform.ADD_WEIGHT if weighted else EdgeTransform.NONE
        )
        self.undirected = undirected
        self.max_iterations = max_iterations
        if track_paths:
            self.compute_keys = ("distance", "predecessor")

    def setup(self, graph, xp):
        idx = xp.arange(graph.local_num_vertices) + graph.global_offset
        dist = xp.where(idx == self.seed_index, 0.0, INF)
        state = {"distance": dist}
        if self.track_paths:
            if graph.num_vertices >= (1 << 24):
                raise ValueError(
                    "track_paths stores vertex indices in float32 state, "
                    "exact only below 2^24 vertices; run distances without "
                    "paths at this scale"
                )
            # seed points at itself; unreached at -1
            state["predecessor"] = xp.where(
                idx == self.seed_index, float(self.seed_index), -1.0
            )
        return state, {"changed": (Combiner.SUM, xp.asarray(1.0))}

    def message(self, state, superstep, graph, xp):
        if self.track_paths:
            idx = xp.arange(graph.local_num_vertices) + graph.global_offset
            on_frontier = state["distance"] == superstep
            return xp.where(on_frontier, idx.astype(state["distance"].dtype), INF)
        if self.weighted:
            return state["distance"]
        return state["distance"] + 1.0

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        old = state["distance"]
        if self.track_paths:
            newly = (old >= INF) & (aggregated < INF)
            dist = xp.where(newly, superstep + 1.0, old)
            pred = xp.where(newly, aggregated, state["predecessor"])
            changed = xp.sum(xp.where(newly, 1.0, 0.0))
            return (
                {"distance": dist, "predecessor": pred},
                {"changed": (Combiner.SUM, changed)},
            )
        new = xp.minimum(old, aggregated)
        changed = xp.sum(xp.where(new < old, 1.0, 0.0))
        return {"distance": new}, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        return memory.get("changed", 1.0) == 0.0

    def terminate_device(self, values, steps_done, xp):
        return values["changed"] == 0.0


def reconstruct_path(result, target_index: int):
    """Walk the predecessor chain host-side: [seed, ..., target], or None if
    the target was never reached. `result` is a run() output of a
    track_paths=True program."""
    import numpy as np

    pred = np.asarray(result["predecessor"]).astype(np.int64)
    dist = np.asarray(result["distance"])
    if target_index >= len(pred) or dist[target_index] >= INF:
        return None
    path = [int(target_index)]
    v = int(target_index)
    for _ in range(len(pred)):
        p = int(pred[v])
        if p < 0:
            return None
        if p == v:  # seed reached
            return list(reversed(path))
        path.append(p)
        v = p
    return None  # cycle guard — malformed predecessor array
