"""Single-source shortest path / BSP BFS (BASELINE config #3 workload).

Reference behavior modeled: TinkerPop ShortestPathVertexProgram as run by
FulgoraGraphComputer (special-cased at FulgoraGraphComputer.java:249-253)
and janusgraph-backend-testutils .../olap/ShortestDistanceVertexProgram.java:
min-combined distance relaxation until fixpoint. Unweighted mode is BFS
hop counting; weighted mode adds the edge weight in flight.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    VertexProgram,
)

INF = 1e18


class ShortestPathProgram(VertexProgram):
    compute_keys = ("distance",)
    combiner = Combiner.MIN
    setup_only_params = ("seed_index",)

    def __init__(
        self,
        seed_index: int,
        weighted: bool = False,
        undirected: bool = False,
        max_iterations: int = 100,
    ):
        self.seed_index = seed_index
        self.weighted = weighted
        self.edge_transform = (
            EdgeTransform.ADD_WEIGHT if weighted else EdgeTransform.NONE
        )
        self.undirected = undirected
        self.max_iterations = max_iterations

    def setup(self, graph, xp):
        idx = xp.arange(graph.local_num_vertices) + graph.global_offset
        dist = xp.where(idx == self.seed_index, 0.0, INF)
        return {"distance": dist}, {"changed": (Combiner.SUM, xp.asarray(1.0))}

    def message(self, state, superstep, graph, xp):
        if self.weighted:
            return state["distance"]
        return state["distance"] + 1.0

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        old = state["distance"]
        new = xp.minimum(old, aggregated)
        changed = xp.sum(xp.where(new < old, 1.0, 0.0))
        return {"distance": new}, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        return memory.get("changed", 1.0) == 0.0

    def terminate_device(self, values, steps_done, xp):
        return values["changed"] == 0.0
