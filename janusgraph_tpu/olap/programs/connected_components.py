"""Connected components by min-label propagation (BASELINE config #2).

Reference behavior modeled: TinkerPop ConnectedComponentVertexProgram via
FulgoraGraphComputer — every vertex starts with its own label and adopts the
minimum label among itself and its (undirected) neighbors until fixpoint.
Labels are global dense vertex indices (exactly representable in float64 up
to 2^53), mapped back to 64-bit vertex ids after the run.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram


class ConnectedComponentsProgram(VertexProgram):
    compute_keys = ("component",)
    combiner = Combiner.MIN
    undirected = True

    def __init__(self, max_iterations: int = 200):
        self.max_iterations = max_iterations

    def setup(self, graph, xp):
        labels = (
            xp.arange(graph.local_num_vertices) + graph.global_offset
        ) * 1.0
        return {"component": labels}, {
            "changed": (Combiner.SUM, xp.asarray(1.0))
        }

    def message(self, state, superstep, graph, xp):
        return state["component"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        old = state["component"]
        new = xp.minimum(old, aggregated)
        changed = xp.sum(xp.where(new < old, 1.0, 0.0))
        return {"component": new}, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        return memory.get("changed", 1.0) == 0.0

    def terminate_device(self, values, steps_done, xp):
        return values["changed"] == 0.0
