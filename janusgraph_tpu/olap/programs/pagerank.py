"""PageRank as array-BSP (BASELINE config #1 workload).

Reference behavior modeled: janusgraph-backend-testutils
.../olap/PageRankVertexProgram.java (damping, out-degree-normalized
contributions, fixed-point iteration). Dangling-vertex rank mass is
redistributed uniformly each superstep; the mass is a global aggregator
computed the superstep before it is consumed, so it is exact under sharding
(one psum, no second pass).
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram


class PageRankProgram(VertexProgram):
    compute_keys = ("rank",)
    combiner = Combiner.SUM

    def __init__(self, damping: float = 0.85, tol: float = 1e-9, max_iterations: int = 30):
        self.damping = damping
        self.tol = tol
        self.max_iterations = max_iterations

    def setup(self, graph, xp):
        n = graph.num_vertices
        active = xp.asarray(graph.active)
        rank = active * (1.0 / n)
        dangling = xp.sum(xp.where(graph.out_degree == 0, rank, 0.0))
        return {"rank": rank}, {"dangling": (Combiner.SUM, dangling)}

    def message(self, state, superstep, graph, xp):
        deg = xp.maximum(graph.out_degree, 1)
        return state["rank"] / deg

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        n = graph.num_vertices
        d = self.damping
        active = xp.asarray(graph.active)
        dangling = memory_in["dangling"]
        # padding slots stay at 0 so global sums (psum) remain exact
        new_rank = active * ((1.0 - d) / n + d * (aggregated + dangling / n))
        delta = xp.sum(xp.abs(new_rank - state["rank"]))
        new_dangling = xp.sum(
            xp.where((graph.out_degree == 0) & (active > 0), new_rank, 0.0)
        )
        return {"rank": new_rank}, {
            "delta": (Combiner.SUM, delta),
            "dangling": (Combiner.SUM, new_dangling),
        }

    def terminate(self, memory):
        return memory.superstep > 1 and memory.get("delta", 1.0) < self.tol

    def terminate_device(self, values, steps_done, xp):
        return xp.logical_and(steps_done > 1, values["delta"] < self.tol)
