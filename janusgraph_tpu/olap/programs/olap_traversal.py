"""OLAP traversal execution: the TraversalVertexProgram analogue.

The reference runs Gremlin traversals OLAP-side by shipping TinkerPop's
TraversalVertexProgram through Fulgora (reference: BASELINE config #5 "3-hop
via TraversalVertexProgram"; FulgoraGraphComputer.submit on a traversal;
SURVEY.md §7 hard part (a) "arbitrary traversers as device state"). The
TPU-native form: a RESTRICTED traversal — a chain of expansion steps, each
with its own direction + edge labels — compiles into one BSP run where
superstep k applies step k's typed EdgeChannel, and per-vertex state is the
dense TRAVERSER COUNT vector (the device representation of "how many
traversers sit here"), exactly what count()/group-count terminals need.
Arbitrary per-traverser state (paths, arbitrary objects) stays an OLTP
concern — the restriction that makes the hot path one gather/segment-reduce
per step.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeChannel,
    VertexProgram,
)


from dataclasses import dataclass


@dataclass(frozen=True)
class PropertyFilter:
    """A mid-chain has()-filter: keep traversers only on vertices whose
    property satisfies the predicate (reference: TraversalVertexProgram
    executes arbitrary Gremlin OLAP-side incl. HasStep —
    FulgoraGraphComputer.java:249-253 submits the full traversal).

    Evaluation is HOST-side over the CSR's property arrays, producing an
    (n,) {0,1} mask shipped to device once (rationale: every predicate —
    Cmp, Text, Geo — works unchanged on any property type; the per-superstep
    device cost is one elementwise multiply, and the mask IS the
    device-resident form of the property column)."""

    key: str
    predicate: object  # a core.predicates.Predicate singleton
    value: object


@dataclass(frozen=True)
class TraversalStep:
    """One expansion: direction out/in/both, optional edge-label ids, and
    optional post-expansion property filters (the `.out().has(...)` shape).
    Frozen/value-comparable so program cache keys (and the executors'
    channel caches) hit across instances built from the same spec."""

    direction: str = "out"
    labels: Optional[Tuple[int, ...]] = None
    filters: Tuple[PropertyFilter, ...] = ()

    def __post_init__(self):
        if self.direction not in ("out", "in", "both"):
            raise ValueError(f"unknown step direction {self.direction!r}")
        if self.labels is not None:
            object.__setattr__(self, "labels", tuple(self.labels))
        object.__setattr__(self, "filters", tuple(self.filters))


def _parse_filters(filters) -> Tuple[PropertyFilter, ...]:
    out = []
    for f in filters or ():
        if isinstance(f, PropertyFilter):
            out.append(f)
        else:
            key, pred, value = f
            out.append(PropertyFilter(key, pred, value))
    return tuple(out)


def steps_from_spec(graph, spec: Sequence) -> Tuple[TraversalStep, ...]:
    """Build steps from spec items, resolving label NAMES to schema ids via
    the graph (None/empty labels = all). Item shapes:
      'out'                                  — expand, all labels
      ('out', ['knows'])                     — expand along labels
      ('out', ['knows'], [(key, pred, v)])   — expand, then has()-filter
    """
    out = []
    for item in spec:
        filters = ()
        if isinstance(item, str):
            direction, labels = item, None
        elif len(item) == 2:
            direction, labels = item
        else:
            direction, labels, filters = item
        ids = None
        if labels:
            ids = []
            for name in labels:
                el = graph.schema_cache.get_by_name(name)
                if el is None:
                    # a typo'd label silently matching nothing would return
                    # a wrong-but-plausible count — fail loudly instead
                    raise ValueError(f"unknown edge label {name!r}")
                ids.append(el.id)
            ids = tuple(ids)
        out.append(TraversalStep(direction, ids, _parse_filters(filters)))
    return tuple(out)


def evaluate_filter_mask(csr, filters: Sequence[PropertyFilter]):
    """AND-combined (n,) float32 {0,1} mask over the CSR's host property
    arrays. Cmp predicates on numeric columns vectorize through numpy; every
    other predicate falls back to the scalar evaluate() loop (correct for
    text/geo/object types)."""
    import numpy as np

    n = csr.num_vertices
    mask = np.ones(n, dtype=np.float32)
    for f in filters:
        col = csr.properties.get(f.key)
        if col is None:
            raise ValueError(
                f"property {f.key!r} not loaded in this CSR snapshot — "
                f"pass property_keys={f.key!r} to load_csr"
            )
        from janusgraph_tpu.core.predicates import _CmpPredicate

        m = None
        if isinstance(f.predicate, _CmpPredicate) and np.issubdtype(
            np.asarray(col).dtype, np.number
        ):
            try:
                with np.errstate(invalid="ignore"):
                    m = f.predicate._fn(np.asarray(col), f.value)
            except TypeError:
                m = None  # mistyped condition: scalar evaluate() decides
        if m is None:
            m = np.fromiter(
                (f.predicate.evaluate(v, f.value) for v in col),
                dtype=bool, count=n,
            )
        mask *= m.astype(np.float32)
    return mask


class OLAPTraversalProgram(VertexProgram):
    """Traverser-count BSP over a step chain.

    state["count"][v] = number of traversers at v after the steps so far
    (float64-safe in f32 up to 2^24 per vertex; overflow means the query
    wants group-counting, not exact enumeration). Starts from all vertices
    (g.V() semantics) or a seed set.

    Terminals on the result:
      total = result["count"].sum()            — g.V().out()...count()
      per-vertex counts                         — group-count by destination
    """

    compute_keys = ("count",)
    combiner = Combiner.SUM
    setup_only_params = ("seed_indices",)

    def __init__(
        self,
        steps: Sequence[TraversalStep],
        seed_indices=None,
        seed_mask=None,
        step_masks=None,
    ):
        """`seed_mask`: (n,) {0,1} array filtering the start set (the
        g.V().has(...) head). `step_masks`: (n, S) array, column k the
        post-expansion filter mask of step k (ones where unfiltered) —
        both prebuilt by `build_olap_traversal` from the steps' filters.
        Masks travel through STATE (not closures) so they ride the jit
        argument path like every other device array (_graph_args lesson:
        big closure constants break remote compile)."""
        self.steps = tuple(steps)
        if not self.steps:
            raise ValueError("at least one traversal step required")
        if step_masks is None and any(st.filters for st in self.steps):
            # running a filter-bearing chain without masks would silently
            # return unfiltered counts — demand the builder
            raise ValueError(
                "steps carry property filters but no step_masks were "
                "built — construct via build_olap_traversal(graph, csr, "
                "spec) so masks are evaluated against the CSR snapshot"
            )
        self.seed_indices = (
            tuple(int(i) for i in seed_indices)
            if seed_indices is not None
            else None
        )
        self._seed_mask = seed_mask
        self._step_masks = step_masks
        self.has_step_masks = step_masks is not None
        self.max_iterations = len(self.steps)
        # one named channel per step; labels=None channels still express
        # per-step direction through the same machinery
        self.edge_channels = {
            f"s{i}": EdgeChannel(st.direction, st.labels)
            for i, st in enumerate(self.steps)
        }

    def channel_for(self, superstep: int) -> str:
        return f"s{min(superstep, len(self.steps) - 1)}"

    def setup(self, graph, xp):
        n = graph.local_num_vertices
        if self.seed_indices is None:
            # `active` masks SPMD padding slots on sharded views (all graph
            # views define it)
            count = xp.ones(n) * graph.active
        else:
            idx = xp.arange(n) + graph.global_offset
            count = xp.isin(idx, xp.asarray(self.seed_indices)).astype(float)
        if self._seed_mask is not None:
            count = count * self._slice_local(self._seed_mask, graph, xp)
        state = {"count": count}
        if self.has_step_masks:
            state["step_masks"] = self._slice_local(
                self._step_masks, graph, xp
            )
        return state, {}

    @staticmethod
    def _slice_local(arr, graph, xp):
        """A mask's shard-local rows: [global_offset, +local_n), zero-padded
        where a sharded view pads past the global vertex count (padding
        slots never hold traversers — `active` already zeroes them)."""
        off = graph.global_offset
        n = graph.local_num_vertices
        a = xp.asarray(arr)
        s = a[off:off + n]
        short = n - s.shape[0]
        if short > 0:
            pad = [(0, short)] + [(0, 0)] * (a.ndim - 1)
            s = xp.pad(s, pad)
        return s

    def message(self, state, superstep, graph, xp):
        return state["count"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        # traversers MOVE: the new count is exactly what arrived — then the
        # step's has()-filter mask zeroes the vertices it rejects. Column
        # select by the (traced) superstep index keeps ONE executable per
        # channel; leading axis stays n so shard-by-vertex layouts hold.
        new = {"count": aggregated}
        if self.has_step_masks:
            masks = state["step_masks"]
            col = xp.clip(superstep, 0, masks.shape[1] - 1)
            new["count"] = aggregated * masks[:, col]
            new["step_masks"] = masks
        return new, {}

    def terminate(self, memory):
        return False  # fixed-length chain; max_iterations bounds the run


def build_olap_traversal(
    graph,
    csr,
    spec: Sequence,
    seeds=None,
    seed_filters=None,
) -> "OLAPTraversalProgram":
    """Compile a filtered traversal spec against a CSR snapshot:
    `g.V().has(seed_filters...).out(...).has(...)...` as one BSP program
    (reference: FulgoraGraphComputer.submit(traversal),
    FulgoraGraphComputer.java:155). Filter predicates evaluate host-side
    over csr.properties into device masks (see PropertyFilter)."""
    import numpy as np

    steps = steps_from_spec(graph, spec)
    seed_mask = None
    if seed_filters:
        seed_mask = evaluate_filter_mask(csr, _parse_filters(seed_filters))
    step_masks = None
    if any(st.filters for st in steps):
        cols = [
            evaluate_filter_mask(csr, st.filters)
            if st.filters
            else np.ones(csr.num_vertices, dtype=np.float32)
            for st in steps
        ]
        step_masks = np.stack(cols, axis=1)  # (n, S): shard-by-vertex axis
    seed_indices = None
    if seeds is not None:
        seed_indices = [csr.index_of(v) for v in seeds]
    return OLAPTraversalProgram(
        steps,
        seed_indices=seed_indices,
        seed_mask=seed_mask,
        step_masks=step_masks,
    )


def group_count_by_label(graph, csr, counts) -> Dict[str, float]:
    """Group-count terminal: traverser totals per vertex LABEL — the
    g.V()...groupCount().by(label) shape (reference: TinkerPop
    GroupCountStep run OLAP-side through TraversalVertexProgram). Host-side
    bincount over the CSR's label column; O(n)."""
    import numpy as np

    if csr.labels is None:
        raise ValueError(
            "CSR snapshot has no vertex-label column — reload with load_csr"
        )
    counts = np.asarray(counts, dtype=np.float64)
    labels = np.asarray(csr.labels)
    out: Dict[str, float] = {}
    for lbl in np.unique(labels):
        total = float(counts[labels == lbl].sum())
        if total == 0.0:
            continue
        el = graph.schema_cache.get_by_id(int(lbl))
        out[el.name if el is not None else str(int(lbl))] = total
    return out
