"""OLAP traversal execution: the TraversalVertexProgram analogue.

The reference runs Gremlin traversals OLAP-side by shipping TinkerPop's
TraversalVertexProgram through Fulgora (reference: BASELINE config #5 "3-hop
via TraversalVertexProgram"; FulgoraGraphComputer.submit on a traversal;
SURVEY.md §7 hard part (a) "arbitrary traversers as device state"). The
TPU-native form: a RESTRICTED traversal — a chain of expansion steps, each
with its own direction + edge labels — compiles into one BSP run where
superstep k applies step k's typed EdgeChannel, and per-vertex state is the
dense TRAVERSER COUNT vector (the device representation of "how many
traversers sit here"), exactly what count()/group-count terminals need.
Arbitrary per-traverser state (paths, arbitrary objects) stays an OLTP
concern — the restriction that makes the hot path one gather/segment-reduce
per step.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeChannel,
    VertexProgram,
)


from dataclasses import dataclass


@dataclass(frozen=True)
class TraversalStep:
    """One expansion: direction out/in/both, optional edge-label ids.
    Frozen/value-comparable so program cache keys (and the executors'
    channel caches) hit across instances built from the same spec."""

    direction: str = "out"
    labels: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.direction not in ("out", "in", "both"):
            raise ValueError(f"unknown step direction {self.direction!r}")
        if self.labels is not None:
            object.__setattr__(self, "labels", tuple(self.labels))


def steps_from_spec(graph, spec: Sequence) -> Tuple[TraversalStep, ...]:
    """Build steps from ('out', ['knows']) pairs, resolving label NAMES to
    schema ids via the graph (None/empty labels = all)."""
    out = []
    for item in spec:
        direction, labels = (item, None) if isinstance(item, str) else item
        ids = None
        if labels:
            ids = []
            for name in labels:
                el = graph.schema_cache.get_by_name(name)
                if el is None:
                    # a typo'd label silently matching nothing would return
                    # a wrong-but-plausible count — fail loudly instead
                    raise ValueError(f"unknown edge label {name!r}")
                ids.append(el.id)
            ids = tuple(ids)
        out.append(TraversalStep(direction, ids))
    return tuple(out)


class OLAPTraversalProgram(VertexProgram):
    """Traverser-count BSP over a step chain.

    state["count"][v] = number of traversers at v after the steps so far
    (float64-safe in f32 up to 2^24 per vertex; overflow means the query
    wants group-counting, not exact enumeration). Starts from all vertices
    (g.V() semantics) or a seed set.

    Terminals on the result:
      total = result["count"].sum()            — g.V().out()...count()
      per-vertex counts                         — group-count by destination
    """

    compute_keys = ("count",)
    combiner = Combiner.SUM
    setup_only_params = ("seed_indices",)

    def __init__(self, steps: Sequence[TraversalStep], seed_indices=None):
        self.steps = tuple(steps)
        if not self.steps:
            raise ValueError("at least one traversal step required")
        self.seed_indices = (
            tuple(int(i) for i in seed_indices)
            if seed_indices is not None
            else None
        )
        self.max_iterations = len(self.steps)
        # one named channel per step; labels=None channels still express
        # per-step direction through the same machinery
        self.edge_channels = {
            f"s{i}": EdgeChannel(st.direction, st.labels)
            for i, st in enumerate(self.steps)
        }

    def channel_for(self, superstep: int) -> str:
        return f"s{min(superstep, len(self.steps) - 1)}"

    def setup(self, graph, xp):
        n = graph.local_num_vertices
        if self.seed_indices is None:
            # `active` masks SPMD padding slots on sharded views (all graph
            # views define it)
            count = xp.ones(n) * graph.active
        else:
            idx = xp.arange(n) + graph.global_offset
            count = xp.isin(idx, xp.asarray(self.seed_indices)).astype(float)
        return {"count": count}, {}

    def message(self, state, superstep, graph, xp):
        return state["count"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        # traversers MOVE: the new count is exactly what arrived
        return {"count": aggregated}, {}

    def terminate(self, memory):
        return False  # fixed-length chain; max_iterations bounds the run
