"""OLAP traversal execution: the TraversalVertexProgram analogue.

The reference runs Gremlin traversals OLAP-side by shipping TinkerPop's
TraversalVertexProgram through Fulgora (reference: BASELINE config #5 "3-hop
via TraversalVertexProgram"; FulgoraGraphComputer.submit on a traversal;
SURVEY.md §7 hard part (a) "arbitrary traversers as device state"). The
TPU-native form: a RESTRICTED traversal — a chain of expansion steps, each
with its own direction + edge labels — compiles into one BSP run where
superstep k applies step k's typed EdgeChannel, and per-vertex state is the
dense TRAVERSER COUNT vector (the device representation of "how many
traversers sit here"), exactly what count()/group-count terminals need.
Arbitrary per-traverser state (paths, arbitrary objects) stays an OLTP
concern — the restriction that makes the hot path one gather/segment-reduce
per step.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeChannel,
    EdgeTransform,
    VertexProgram,
)


from dataclasses import dataclass


@dataclass(frozen=True)
class PropertyFilter:
    """A mid-chain has()-filter: keep traversers only on vertices whose
    property satisfies the predicate (reference: TraversalVertexProgram
    executes arbitrary Gremlin OLAP-side incl. HasStep —
    FulgoraGraphComputer.java:249-253 submits the full traversal).

    Evaluation is HOST-side over the CSR's property arrays, producing an
    (n,) {0,1} mask shipped to device once (rationale: every predicate —
    Cmp, Text, Geo — works unchanged on any property type; the per-superstep
    device cost is one elementwise multiply, and the mask IS the
    device-resident form of the property column)."""

    key: str
    predicate: object  # a core.predicates.Predicate singleton
    value: object


@dataclass(frozen=True)
class TraversalStep:
    """One expansion: direction out/in/both, optional edge-label ids, and
    optional post-expansion property filters (the `.out().has(...)` shape).
    Frozen/value-comparable so program cache keys (and the executors'
    channel caches) hit across instances built from the same spec."""

    direction: str = "out"
    labels: Optional[Tuple[int, ...]] = None
    filters: Tuple[PropertyFilter, ...] = ()
    #: step label for select() over enumerated paths (the as() tag of
    #: TinkerPop; reference: TraversalVertexProgram carrying path labels)
    as_label: Optional[str] = None

    def __post_init__(self):
        if self.direction not in ("out", "in", "both"):
            raise ValueError(f"unknown step direction {self.direction!r}")
        if self.labels is not None:
            object.__setattr__(self, "labels", tuple(self.labels))
        object.__setattr__(self, "filters", tuple(self.filters))


def _parse_filters(filters) -> Tuple[PropertyFilter, ...]:
    out = []
    for f in filters or ():
        if isinstance(f, PropertyFilter):
            out.append(f)
        else:
            key, pred, value = f
            out.append(PropertyFilter(key, pred, value))
    return tuple(out)


def steps_from_spec(graph, spec: Sequence) -> Tuple[TraversalStep, ...]:
    """Build steps from spec items, resolving label NAMES to schema ids via
    the graph (None/empty labels = all). Item shapes:
      'out'                                  — expand, all labels
      ('out', ['knows'])                     — expand along labels
      ('out', ['knows'], [(key, pred, v)])   — expand, then has()-filter
      ('out', ['knows'], [...], 'b')         — ... and as('b')-tag the step
    """
    out = []
    for item in spec:
        filters = ()
        as_label = None
        if isinstance(item, str):
            direction, labels = item, None
        elif len(item) == 2:
            direction, labels = item
        elif len(item) == 3:
            direction, labels, filters = item
        else:
            direction, labels, filters, as_label = item
        ids = None
        if labels:
            ids = []
            for name in labels:
                el = graph.schema_cache.get_by_name(name)
                if el is None:
                    # a typo'd label silently matching nothing would return
                    # a wrong-but-plausible count — fail loudly instead
                    raise ValueError(f"unknown edge label {name!r}")
                ids.append(el.id)
            ids = tuple(ids)
        out.append(
            TraversalStep(direction, ids, _parse_filters(filters), as_label)
        )
    return tuple(out)


def evaluate_filter_mask(csr, filters: Sequence[PropertyFilter]):
    """AND-combined (n,) float32 {0,1} mask over the CSR's host property
    arrays. Cmp predicates on numeric columns vectorize through numpy; every
    other predicate falls back to the scalar evaluate() loop (correct for
    text/geo/object types)."""
    import numpy as np

    n = csr.num_vertices
    mask = np.ones(n, dtype=np.float32)
    for f in filters:
        col = csr.properties.get(f.key)
        if col is None:
            raise ValueError(
                f"property {f.key!r} not loaded in this CSR snapshot — "
                f"pass property_keys={f.key!r} to load_csr"
            )
        from janusgraph_tpu.core.predicates import _CmpPredicate

        m = None
        if isinstance(f.predicate, _CmpPredicate) and np.issubdtype(
            np.asarray(col).dtype, np.number
        ):
            try:
                with np.errstate(invalid="ignore"):
                    m = f.predicate._fn(np.asarray(col), f.value)
            except TypeError:
                m = None  # mistyped condition: scalar evaluate() decides
        if m is None:
            m = np.fromiter(
                (f.predicate.evaluate(v, f.value) for v in col),
                dtype=bool, count=n,
            )
        mask *= m.astype(np.float32)
    return mask


class OLAPTraversalProgram(VertexProgram):
    """Traverser-count BSP over a step chain.

    state["count"][v] = number of traversers at v after the steps so far
    (float64-safe in f32 up to 2^24 per vertex; overflow means the query
    wants group-counting, not exact enumeration). Starts from all vertices
    (g.V() semantics) or a seed set.

    Terminals on the result:
      total = result["count"].sum()            — g.V().out()...count()
      per-vertex counts                         — group-count by destination
    """

    compute_keys = ("count",)
    combiner = Combiner.SUM
    setup_only_params = ("seed_indices",)

    def __init__(
        self,
        steps: Sequence[TraversalStep],
        seed_indices=None,
        seed_mask=None,
        step_masks=None,
        record_reach: bool = False,
        sack: Optional[str] = None,
        sack_init: Optional[float] = None,
    ):
        """`seed_mask`: (n,) {0,1} array filtering the start set (the
        g.V().has(...) head). `step_masks`: (n, S) array, column k the
        post-expansion filter mask of step k (ones where unfiltered) —
        both prebuilt by `build_olap_traversal` from the steps' filters.
        Masks travel through STATE (not closures) so they ride the jit
        argument path like every other device array (_graph_args lesson:
        big closure constants break remote compile)."""
        self.steps = tuple(steps)
        if not self.steps:
            raise ValueError("at least one traversal step required")
        if step_masks is None and any(st.filters for st in self.steps):
            # running a filter-bearing chain without masks would silently
            # return unfiltered counts — demand the builder
            raise ValueError(
                "steps carry property filters but no step_masks were "
                "built — construct via build_olap_traversal(graph, csr, "
                "spec) so masks are evaluated against the CSR snapshot"
            )
        self.seed_indices = (
            tuple(int(i) for i in seed_indices)
            if seed_indices is not None
            else None
        )
        self._seed_mask = seed_mask
        self._step_masks = step_masks
        self.has_step_masks = step_masks is not None
        #: device-side half of path()/select(): record, per superstep, the
        #: {0,1} mask of vertices holding >=1 traverser — the per-level
        #: reachability host enumeration walks backward over
        #: (enumerate_paths; SURVEY §7 hard part (a)'s hybrid design)
        self.record_reach = record_reach
        #: OLAP-side sack (TinkerPop withSack().sack(op).by('weight')):
        #: state["sack"][v] = total sack mass of the traversers at v.
        #:   "sum"  — each hop adds the edge weight per traverser:
        #:            S'[v] = Σ_{u→v} (S[u] + w·c[u]); message columns
        #:            [count, sack, count] ride per-column transforms
        #:            (NONE, NONE, MUL_WEIGHT) — the third column carries
        #:            the cross-term Σ w·c (apply_edge_transform)
        #:   "mult" — each hop multiplies by the edge weight:
        #:            S'[v] = Σ S[u]·w; columns [count, sack] with
        #:            (NONE, MUL_WEIGHT)
        if sack not in (None, "sum", "mult"):
            raise ValueError(f"unknown sack op {sack!r} (sum|mult)")
        self.sack = sack
        self.sack_init = (
            sack_init if sack_init is not None
            else (0.0 if sack == "sum" else 1.0)
        )
        if sack == "sum":
            self.edge_transform_cols = (
                EdgeTransform.NONE, EdgeTransform.NONE,
                EdgeTransform.MUL_WEIGHT,
            )
        elif sack == "mult":
            self.edge_transform_cols = (
                EdgeTransform.NONE, EdgeTransform.MUL_WEIGHT,
            )
        self.max_iterations = len(self.steps)
        # one named channel per step; labels=None channels still express
        # per-step direction through the same machinery
        self.edge_channels = {
            f"s{i}": EdgeChannel(st.direction, st.labels)
            for i, st in enumerate(self.steps)
        }

    def channel_for(self, superstep: int) -> str:
        return f"s{min(superstep, len(self.steps) - 1)}"

    def setup(self, graph, xp):
        n = graph.local_num_vertices
        if self.seed_indices is None:
            # `active` masks SPMD padding slots on sharded views (all graph
            # views define it)
            count = xp.ones(n) * graph.active
        else:
            idx = xp.arange(n) + graph.global_offset
            count = xp.isin(idx, xp.asarray(self.seed_indices)).astype(float)
        if self._seed_mask is not None:
            count = count * self._slice_local(self._seed_mask, graph, xp)
        state = {"count": count}
        if self.sack is not None:
            state["sack"] = count * self.sack_init
        if self.has_step_masks:
            state["step_masks"] = self._slice_local(
                self._step_masks, graph, xp
            )
        if self.record_reach:
            # column k = mask after step k (column 0: the seed set)
            ncols = len(self.steps) + 1
            reach = xp.zeros((n, ncols), dtype=count.dtype)
            onehot = (xp.arange(ncols) == 0).astype(count.dtype)
            reach = reach + (count > 0).astype(count.dtype)[:, None] * onehot
            state["reach"] = reach
        return state, {}

    @staticmethod
    def _slice_local(arr, graph, xp):
        """A mask's shard-local rows: [global_offset, +local_n), zero-padded
        where a sharded view pads past the global vertex count (padding
        slots never hold traversers — `active` already zeroes them)."""
        off = graph.global_offset
        n = graph.local_num_vertices
        a = xp.asarray(arr)
        s = a[off:off + n]
        short = n - s.shape[0]
        if short > 0:
            pad = [(0, short)] + [(0, 0)] * (a.ndim - 1)
            s = xp.pad(s, pad)
        return s

    def message(self, state, superstep, graph, xp):
        if self.sack == "sum":
            # [count, sack, count]: the 3rd column rides MUL_WEIGHT and
            # aggregates to the cross-term Σ w·c (see __init__)
            return xp.stack(
                [state["count"], state["sack"], state["count"]], axis=1
            )
        if self.sack == "mult":
            return xp.stack([state["count"], state["sack"]], axis=1)
        return state["count"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        # traversers MOVE: the new count is exactly what arrived — then the
        # step's has()-filter mask zeroes the vertices it rejects. Column
        # select by the (traced) superstep index keeps ONE executable per
        # channel; leading axis stays n so shard-by-vertex layouts hold.
        if self.sack == "sum":
            new = {
                "count": aggregated[:, 0],
                # S' = Σ S[u] + Σ w·c[u]
                "sack": aggregated[:, 1] + aggregated[:, 2],
            }
        elif self.sack == "mult":
            new = {"count": aggregated[:, 0], "sack": aggregated[:, 1]}
        else:
            new = {"count": aggregated}
        if self.has_step_masks:
            masks = state["step_masks"]
            col = xp.clip(superstep, 0, masks.shape[1] - 1)
            new["count"] = new["count"] * masks[:, col]
            if self.sack is not None:
                # rejected traversers take their sack mass with them
                new["sack"] = new["sack"] * masks[:, col]
            new["step_masks"] = masks
        if self.record_reach:
            # one-hot column write (xp-agnostic: no .at[] in numpy) —
            # column superstep+1 becomes this step's arrival mask
            reach = state["reach"]
            ncols = reach.shape[1]
            col1 = xp.clip(superstep, 0, ncols - 2) + 1
            onehot = (xp.arange(ncols) == col1).astype(reach.dtype)
            arrived = (new["count"] > 0).astype(reach.dtype)
            new["reach"] = (
                reach * (1.0 - onehot)[None, :]
                + arrived[:, None] * onehot[None, :]
            )
        return new, {}

    def terminate(self, memory):
        return False  # fixed-length chain; max_iterations bounds the run


def build_olap_traversal(
    graph,
    csr,
    spec: Sequence,
    seeds=None,
    seed_filters=None,
    record_reach: bool = False,
    sack: Optional[str] = None,
    sack_init: Optional[float] = None,
) -> "OLAPTraversalProgram":
    """Compile a filtered traversal spec against a CSR snapshot:
    `g.V().has(seed_filters...).out(...).has(...)...` as one BSP program
    (reference: FulgoraGraphComputer.submit(traversal),
    FulgoraGraphComputer.java:155). Filter predicates evaluate host-side
    over csr.properties into device masks (see PropertyFilter)."""
    import numpy as np

    steps = steps_from_spec(graph, spec)
    seed_mask = None
    if seed_filters:
        seed_mask = evaluate_filter_mask(csr, _parse_filters(seed_filters))
    step_masks = None
    if any(st.filters for st in steps):
        cols = [
            evaluate_filter_mask(csr, st.filters)
            if st.filters
            else np.ones(csr.num_vertices, dtype=np.float32)
            for st in steps
        ]
        step_masks = np.stack(cols, axis=1)  # (n, S): shard-by-vertex axis
    seed_indices = None
    if seeds is not None:
        seed_indices = [csr.index_of(v) for v in seeds]
    if sack is not None and (
        csr.in_edge_weight is None and csr.out_edge_weight is None
    ):
        # fail fast like TinkerPop's .by('weight') on a missing key —
        # silently folding w=1 would produce plausible wrong numbers
        raise ValueError(
            f"sack={sack!r} folds edge weights but the CSR snapshot "
            "carries none — load with compute().weight(<property key>)"
        )
    return OLAPTraversalProgram(
        steps,
        seed_indices=seed_indices,
        seed_mask=seed_mask,
        step_masks=step_masks,
        record_reach=record_reach,
        sack=sack,
        sack_init=sack_init,
    )


def build_path_index(csr, program):
    """The per-step reverse adjacency enumerate_paths walks: one
    O(E log E) sort per step. Build ONCE per (csr, program) and reuse —
    ComputerResult memoizes it so paths() + select() on the same result
    don't pay it twice."""
    import numpy as np

    from janusgraph_tpu.olap.csr import channel_edges

    n = csr.num_vertices
    rev = []
    for k in range(len(program.steps)):
        src, dst, _w = channel_edges(csr, program.edge_channels[f"s{k}"])
        order = np.argsort(dst, kind="stable")
        srcs = src[order]
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(np.bincount(dst, minlength=n), out=indptr[1:])
        rev.append((indptr, srcs))
    return rev


def enumerate_paths(csr, program, states, limit=None, path_index=None):
    """Host half of OLAP path(): lazily enumerate the traverser paths of a
    `record_reach` run, as tuples of GRAPH vertex ids (seed first).

    Hybrid design (SURVEY §7 hard part (a); reference:
    FulgoraGraphComputer.java:155 shipping TraversalVertexProgram with
    per-traverser path objects): the DEVICE ran the frontier expansion and
    recorded per-step reach masks — exact reachability, counts > 0 — and
    the HOST walks them backward over each step's edge view. A backward
    neighbor u of v at level k-1 with reach[u, k-1] set lies on a real
    seed-to-v path, so the DFS emits exactly the OLTP traverser paths
    (parallel edges yield one path per edge instance, like OLTP
    traversers). Cost is O(paths emitted) adjacency probes after an
    O(E log E) per-step reverse-sort — independent of |V| once built.

    Generator: bound it with `limit` (3-hop path counts explode on dense
    graphs; the device-side `count` sum prices the enumeration first).
    """
    import numpy as np

    reach = np.asarray(states["reach"]) > 0          # (n, S+1)
    S = len(program.steps)
    # path_index may be a zero-arg callable (memoized builder): the
    # O(E log E) build then happens on FIRST ITERATION, after cheap
    # validation (unknown select() names must not pay for the sorts)
    if callable(path_index):
        path_index = path_index()
    rev = path_index if path_index is not None else build_path_index(
        csr, program
    )
    vids = csr.vertex_ids

    def back(v, k):
        if k == 0:
            yield (v,)
            return
        indptr, srcs = rev[k - 1]
        for u in srcs[indptr[v]: indptr[v + 1]]:
            if reach[u, k - 1]:
                for prefix in back(int(u), k - 1):
                    yield prefix + (v,)

    emitted = 0
    if limit is not None and limit <= 0:
        return
    for v in np.nonzero(reach[:, S])[0]:
        for p in back(int(v), S):
            yield tuple(int(vids[i]) for i in p)
            emitted += 1
            if limit is not None and emitted >= limit:
                return


def select_paths(
    csr, program, states, names, source_as=None, limit=None, path_index=None,
):
    """select() over enumerated paths: project the as()-labeled positions
    of each path into a dict (reference: TinkerPop SelectStep consuming
    step labels). `source_as` names path position 0 (the g.V() head)."""
    positions = {}
    if source_as is not None:
        positions[source_as] = 0
    for i, st in enumerate(program.steps):
        if st.as_label is not None:
            if st.as_label in positions:
                # TinkerPop collects duplicated labels into lists; this
                # projection is single-valued — refuse rather than
                # silently dropping the earlier binding
                raise ValueError(
                    f"duplicate as()-label {st.as_label!r} — give each "
                    "selected step a distinct label"
                )
            positions[st.as_label] = i + 1
    missing = [nm for nm in names if nm not in positions]
    if missing:
        raise ValueError(
            f"select() names {missing} match no as()-labeled step "
            f"(labeled: {sorted(positions)})"
        )
    for p in enumerate_paths(
        csr, program, states, limit=limit, path_index=path_index,
    ):
        yield {nm: p[positions[nm]] for nm in names}


def group_count_by_label(graph, csr, counts) -> Dict[str, float]:
    """Group-count terminal: traverser totals per vertex LABEL — the
    g.V()...groupCount().by(label) shape (reference: TinkerPop
    GroupCountStep run OLAP-side through TraversalVertexProgram). Host-side
    bincount over the CSR's label column; O(n)."""
    import numpy as np

    if csr.labels is None:
        raise ValueError(
            "CSR snapshot has no vertex-label column — reload with load_csr"
        )
    counts = np.asarray(counts, dtype=np.float64)
    labels = np.asarray(csr.labels)
    out: Dict[str, float] = {}
    for lbl in np.unique(labels):
        total = float(counts[labels == lbl].sum())
        if total == 0.0:
            continue
        el = graph.schema_cache.get_by_id(int(lbl))
        out[el.name if el is not None else str(int(lbl))] = total
    return out
