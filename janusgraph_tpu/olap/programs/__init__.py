from janusgraph_tpu.olap.programs.pagerank import PageRankProgram  # noqa: F401
from janusgraph_tpu.olap.programs.shortest_path import ShortestPathProgram  # noqa: F401
from janusgraph_tpu.olap.programs.connected_components import (  # noqa: F401
    ConnectedComponentsProgram,
)
from janusgraph_tpu.olap.programs.traversal_count import (  # noqa: F401
    TraversalCountProgram,
)
from janusgraph_tpu.olap.programs.peer_pressure import PeerPressureProgram  # noqa: F401
from janusgraph_tpu.olap.programs.olap_traversal import (  # noqa: F401
    OLAPTraversalProgram,
    TraversalStep,
    steps_from_spec,
)
from janusgraph_tpu.olap.programs.degree import DegreeCountProgram  # noqa: F401
from janusgraph_tpu.olap.programs.gcn import GCNForwardProgram  # noqa: F401
from janusgraph_tpu.olap.programs.embedding import (  # noqa: F401
    EmbeddingUpdateProgram,
)
