"""2-layer GCN forward pass as a dense-feature vertex program.

Each superstep is one GCN layer: gather neighbor feature rows (plus the
self row), mean-normalize by in-degree, then the dense transform
``act(norm @ W_l + b_l)`` — the fused SDDMM–SpMM superstep shape of
FusedMM (PAPERS.md arxiv 2011.06391), with the matmul as the MXU op.
``attention=True`` switches the gather to the sddmm mode: per-edge
dot-attention coefficients ``<h_src, h_dst>`` fused into the same pass
(a GAT-flavored layer on the identical kernel).

Weights are seeded deterministically (or passed in), embedded into
(d_pad, d_pad) lane-tier blocks with zero padding, and stacked so the
traced superstep indexes layer l with the traced superstep scalar — one
compiled superstep serves every layer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from janusgraph_tpu.olap.features.dense_program import (
    DenseVertexProgram,
    MessageMode,
)
from janusgraph_tpu.olap.features.kernels import (
    matmul_flops,
    pad_features,
    pick_feature_tier,
    sddmm_flops,
)
from janusgraph_tpu.olap.vertex_program import Combiner


class GCNForwardProgram(DenseVertexProgram):
    """Forward pass of an L-layer GCN (default 2) over the CSR snapshot.

    State: ``h`` — the (n, d_pad) feature block after the layers run so
    far. ``terminate`` stops after ``num_layers`` supersteps; the device
    predicate mirrors it, so the fused while_loop path applies."""

    feature_keys = ("h",)

    def __init__(
        self,
        feature_dim: int = 16,
        hidden_dim: int = 16,
        out_dim: int = 16,
        num_layers: int = 2,
        seed: int = 7,
        activation: str = "relu",
        attention: bool = False,
        weighted: bool = False,
        weights: Optional[Sequence[np.ndarray]] = None,
        dim_tier: int = 0,
        native_matmul: bool = False,
    ):
        if attention and weighted:
            raise ValueError("attention and weighted are mutually exclusive")
        if attention:
            self.message_mode = MessageMode.SDDMM
        elif weighted:
            self.message_mode = MessageMode.WEIGHTED
        super().__init__(
            feature_dim, dim_tier=dim_tier, native_matmul=native_matmul
        )
        self.hidden_dim = int(hidden_dim)
        self.out_dim = int(out_dim)
        self.num_layers = int(num_layers)
        if self.num_layers < 1:
            raise ValueError("num_layers must be >= 1")
        self.seed = int(seed)
        self.activation = activation
        self.max_iterations = self.num_layers
        self._dims = (
            [self.feature_dim]
            + [self.hidden_dim] * (self.num_layers - 1)
            + [self.out_dim]
        )
        self._max_dim = max(self._dims)
        self.d_pad = pick_feature_tier(self._max_dim, self.dim_tier)
        self._build_weights(weights)

    def set_dim_tier(self, tier: int) -> None:
        self.dim_tier = int(tier or 0)
        self.d_pad = pick_feature_tier(self._max_dim, self.dim_tier)
        self._build_weights(self._given_weights)

    def _build_weights(self, weights) -> None:
        """Stack per-layer (d_pad, d_pad)/(d_pad,) weight/bias blocks —
        real coefficients in the top-left (d_l, d_{l+1}) corner, zeros in
        the padding so padded feature columns stay zero through layers."""
        self._given_weights = weights
        dp = self.d_pad
        rng = np.random.default_rng(self.seed)
        w_stack = np.zeros((self.num_layers, dp, dp), dtype=np.float32)
        b_stack = np.zeros((self.num_layers, dp), dtype=np.float32)
        for layer in range(self.num_layers):
            d_in, d_out = self._dims[layer], self._dims[layer + 1]
            if weights is not None:
                w = np.asarray(weights[layer], dtype=np.float32)
                if w.shape != (d_in, d_out):
                    raise ValueError(
                        f"layer {layer} weights {w.shape} != ({d_in}, {d_out})"
                    )
            else:
                w = (
                    rng.standard_normal((d_in, d_out)) / np.sqrt(d_in)
                ).astype(np.float32)
            w_stack[layer, :d_in, :d_out] = w
            b_stack[layer, :d_out] = (
                rng.standard_normal(d_out) * 0.01
            ).astype(np.float32)
        self._w_stack = w_stack
        self._b_stack = b_stack

    # ----------------------------------------------------------------- BSP
    def setup(self, graph, xp):
        n = graph.num_vertices
        rng = np.random.default_rng(self.seed + 1)
        x = rng.standard_normal((n, self.feature_dim)).astype(np.float32)
        h = pad_features(x, self.d_pad)
        # padded-vertex rows (sharded executor: local_num_vertices >= n)
        # are zero and stay zero — drawn AFTER the real rows so the
        # feature matrix is bit-identical across executors/mesh sizes
        local = getattr(graph, "local_num_vertices", n)
        if local > n:
            h = np.vstack([h, np.zeros((local - n, h.shape[1]), h.dtype)])
        return {"h": xp.asarray(h)}, {
            "h_norm": (Combiner.SUM, float(np.abs(h).sum())),
        }

    def message(self, state, superstep, graph, xp):
        return state["h"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        h = state["h"]
        indeg = xp.asarray(graph.in_degree, dtype=h.dtype)
        # mean aggregation with a self loop: (sum_in + h) / (indeg + 1)
        norm = (aggregated + h) / (xp.maximum(indeg, 0.0) + 1.0)[:, None]
        w = xp.asarray(self._w_stack, dtype=h.dtype)[superstep]
        b = xp.asarray(self._b_stack, dtype=h.dtype)[superstep]
        h2 = self.dense_layer(xp, norm, w, b, self.activation)
        return {"h": h2}, {
            "h_norm": (Combiner.SUM, xp.sum(xp.abs(h2))),
        }

    def terminate(self, memory):
        return memory.superstep >= self.num_layers

    def terminate_device(self, values, steps_done, xp):
        return xp.asarray(steps_done >= self.num_layers)

    # ---------------------------------------------------------------- cost
    def matmul_flops(self, num_vertices: int, num_edges: int) -> float:
        flops = matmul_flops(num_vertices, self.d_pad, self.d_pad)
        if self.message_mode == MessageMode.SDDMM:
            flops += sddmm_flops(num_edges, self.d_pad)
        return flops
