"""Degree-count vertex program (reference: the degree-counting vertex
programs exercised by janusgraph-test graphdb/olap/OLAPTest.java:779 — the
simplest one-superstep message-count program, also the canonical smoke test
for a GraphComputer implementation).

One superstep: every vertex sends 1 along its out-edges; SUM-combining at
the receiver yields the in-degree. The out-degree is already a dense CSR
array, so both orientations land as compute keys in a single pass.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram


class DegreeCountProgram(VertexProgram):
    compute_keys = ("in_degree", "out_degree")
    combiner = Combiner.SUM
    max_iterations = 1

    def setup(self, graph, xp):
        n = graph.num_vertices
        zeros = xp.zeros(n, dtype=xp.float32)
        return (
            {
                "in_degree": zeros,
                "out_degree": xp.asarray(graph.out_degree, dtype=xp.float32),
            },
            {"total": (Combiner.SUM, xp.sum(xp.asarray(graph.out_degree)))},
        )

    def message(self, state, superstep, graph, xp):
        # every vertex contributes 1 per out-edge
        return xp.ones(graph.local_num_vertices, dtype=xp.float32)

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        return (
            {"in_degree": aggregated, "out_degree": state["out_degree"]},
            {"total": (Combiner.SUM, xp.sum(aggregated))},
        )

    def terminate(self, memory):
        return memory.superstep >= 1

    def terminate_device(self, values, steps_done, xp):
        return steps_done >= 1
