"""Peer-pressure community detection / label propagation (BASELINE #4).

Reference behavior modeled: TinkerPop PeerPressureVertexProgram via
FulgoraGraphComputer — each vertex repeatedly adopts the most frequent
cluster label among its neighbors until stable.

The mode (most-frequent) reduction is not a per-message monoid, so it cannot
be one segment-reduce. TPU-first formulation: two alternating phases, each a
monoid reduce over fixed-width messages:

  phase A (SUM): neighbors send a one-hot over K label buckets; the count
    vector's argmax picks the winning bucket per vertex.
  phase B (MIN): neighbors send their label masked into its bucket slot
    (inf elsewhere); each vertex adopts the minimum label present in its
    winning bucket.

With K >= number of live labels the result is exact mode-with-min-tiebreak;
smaller K trades memory for bucket-collision approximation (documented
divergence; exactness is asserted in tests with ample K).
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram

INF = 1e18


class PeerPressureProgram(VertexProgram):
    compute_keys = ("cluster",)
    undirected = True

    def __init__(self, num_buckets: int = 64, rounds: int = 30):
        self.K = num_buckets
        self.rounds = rounds
        self.max_iterations = rounds * 2

    def combiner_for(self, superstep: int) -> str:
        return Combiner.SUM if superstep % 2 == 0 else Combiner.MIN

    def _bucket(self, labels, xp):
        return xp.mod(labels.astype(xp.int32), self.K)

    def setup(self, graph, xp):
        labels = (
            xp.arange(graph.local_num_vertices) + graph.global_offset
        ) * 1.0
        chosen = self._bucket(labels, xp)
        return (
            {"cluster": labels, "chosen": chosen},
            {"changed": (Combiner.SUM, xp.asarray(1.0))},
        )

    def message(self, state, superstep, graph, xp):
        labels = state["cluster"]
        k = xp.arange(self.K)
        onehot = (self._bucket(labels, xp)[:, None] == k[None, :])
        if hasattr(superstep, "dtype"):  # traced: select by parity
            is_count = xp.equal(xp.mod(superstep, 2), 0)
            count_msg = xp.where(onehot, 1.0, 0.0)
            label_msg = xp.where(onehot, labels[:, None], INF)
            return xp.where(is_count, count_msg, label_msg)
        if superstep % 2 == 0:
            return xp.where(onehot, 1.0, 0.0)
        return xp.where(onehot, labels[:, None], INF)

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        def count_phase():
            # argmax with lowest-bucket tiebreak; vertices with no neighbors
            # keep their own bucket
            counts = aggregated
            best = xp.argmax(counts, axis=1).astype(xp.int32)
            has_neighbors = xp.sum(counts, axis=1) > 0
            chosen = xp.where(has_neighbors, best, state["chosen"])
            return {"cluster": state["cluster"], "chosen": chosen}, 1.0

        def resolve_phase():
            n_local = aggregated.shape[0]
            rows = xp.arange(n_local)
            candidate = aggregated[rows, state["chosen"]]
            new = xp.where(candidate < INF, candidate, state["cluster"])
            # adopt only if it is at least as frequent — peer pressure moves
            # toward neighborhood consensus, including label switches
            changed = xp.sum(xp.where(new != state["cluster"], 1.0, 0.0))
            return {"cluster": new, "chosen": state["chosen"]}, changed

        if hasattr(superstep, "dtype"):
            import jax

            (new_state, changed) = jax.lax.cond(
                (superstep % 2) == 0,
                lambda: count_phase(),
                lambda: resolve_phase(),
            )
        else:
            new_state, changed = (
                count_phase() if superstep % 2 == 0 else resolve_phase()
            )
        return new_state, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        # stop after a resolve phase in which nothing changed
        return memory.superstep % 2 == 0 and memory.superstep > 1 and memory.get(
            "changed", 1.0
        ) == 0.0

    def terminate_device(self, values, steps_done, xp):
        return xp.logical_and(
            xp.logical_and(steps_done % 2 == 0, steps_done > 1),
            values["changed"] == 0.0,
        )
