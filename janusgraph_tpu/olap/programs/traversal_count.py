"""K-hop traversal counting — the OLAP-traversal workload shape
(BASELINE config #5: Gremlin 3-hop traversal via TraversalVertexProgram).

Reference behavior modeled: TinkerPop TraversalVertexProgram running
g.V().out().out().out().count() on Fulgora — traverser bulks are per-vertex
counts, each hop is one message round, the answer is the global bulk sum.
This is the fixed-width-numeric projection of traverser propagation
(SURVEY.md §7 hard part (a)); arbitrary-state traversers remain on the OLTP
path.
"""

from __future__ import annotations

from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram


class TraversalCountProgram(VertexProgram):
    """After k supersteps, state['count'][i] = number of k-hop paths ending
    at vertex i; the global path count is their sum (psum on a mesh)."""

    compute_keys = ("count",)
    combiner = Combiner.SUM

    def __init__(self, hops: int, labels=None):
        self.max_iterations = hops
        self.hops = hops
        self.labels = labels  # edge-label restriction applied at CSR load

    def setup(self, graph, xp):
        counts = xp.asarray(graph.active) * 1.0  # padding starts at 0 paths
        return {"count": counts}, {"total": (Combiner.SUM, xp.sum(counts))}

    def message(self, state, superstep, graph, xp):
        return state["count"]

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        return {"count": aggregated}, {"total": (Combiner.SUM, xp.sum(aggregated))}

    def terminate(self, memory):
        return memory.superstep >= self.hops

    def terminate_device(self, values, steps_done, xp):
        return steps_done >= self.hops
