"""OLTP->OLAP spillover: compile hot multi-hop traversals to frontier/
SpGEMM supersteps over a cached CSR snapshot.

The paper's OLTP engine walks ``g.V().out().out()...`` row by row through
the property layer while the OLAP engine already executes the same
adjacency math as vectorized frontier expansion over a CSR snapshot —
ALPHA-PIM and the structured-SpGEMM papers (PAPERS.md) both frame
multi-hop graph queries as sparse matrix products that are orders of
magnitude cheaper in bulk form. This module is the planner that routes
recurring expensive shapes onto the OLAP executor:

- **Recognition** (:func:`recognize`): a compilable chain is a
  ``V()``/``V(ids)`` start (optionally label-filtered), a sequence of
  ``out/in/both[E]`` hops with edge-label filters (plus mid-chain
  ``has_label`` vertex filters), terminated by ``count``/``dedup``/``id``
  -style reducers. Anything else is an unsupported step and falls back.

- **Promotion policy**: the PR 5 :class:`~janusgraph_tpu.observability.
  profiler.DigestTable` already measures per-shape mean cost; a shape is
  promoted once its measured mean wall exceeds
  ``computer.spillover-min-cost-ms`` over at least
  ``computer.spillover-min-seen`` executions. Promotion is sticky for the
  planner's lifetime (a spilled shape's now-cheap walls must not demote
  it back into the slow path — that would flap).

- **Execution**: the chain compiles to an
  :class:`~janusgraph_tpu.olap.programs.olap_traversal.
  OLAPTraversalProgram` (one typed EdgeChannel per hop, traverser-count
  state) and runs on the configured OLAP executor over a CACHED CSR
  snapshot — packed once, incrementally refreshed through the backend's
  mutation-epoch tracker while committed writes stay within
  ``computer.spillover-max-staleness``, dropped for a repack beyond it
  (counter ``olap.spillover.stale`` — the bounded-staleness groundwork
  for the streaming delta-CSR item). ``computer.sharded-auto`` routes
  multi-device processes to the sharded executor exactly like
  ``graph.compute()``.

- **Tx-overlay reconciliation** (read-your-writes): the transaction's
  uncommitted adds/deletes — the existence-cell machinery already sees
  every mutation — are merged into the snapshot BEFORE the run by
  patching the edge multiset (delete tombstoned instances, append added
  edges, extend the vertex set with uncommitted vertices), so spilled
  results are set-equal to the step-by-step walk even mid-transaction.
  Overlays beyond ``computer.spillover-max-overlay`` fall back.

- **Fallback is always safe**: any unsupported step, overlay overflow,
  staleness breach, rung-2 brownout (``check_olap_admission``), count
  overflow past float32 exactness, or unexpected error returns ``None``
  to the caller — the row-by-row walk continues unchanged — with a
  ``spillover_fallback`` flight event and a per-reason counter.

Hooked from :meth:`GraphTraversal._execute` (and the ``count()``
terminal) via :func:`try_spill`; built per graph at open when
``computer.spillover`` is set (core/graph.py).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: process-wide promoted-digest set: GET /profile marks table rows whose
#: digest any live planner has promoted
_PROMOTED_LOCK = threading.Lock()
_PROMOTED_GLOBAL: set = set()


def promoted_digests() -> set:
    with _PROMOTED_LOCK:
        return set(_PROMOTED_GLOBAL)


class _SpillRefused(Exception):
    """Internal control flow: this attempt falls back (reason carried)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclass
class SpilloverPlan:
    """One recognized compilable chain."""

    digest: str
    shape: str
    #: [(direction, edge-label names or None, [vertex-label tuples])]
    hops: List[Tuple[str, Optional[Tuple[str, ...]], List[Tuple[str, ...]]]]
    #: explicit V(ids) seeds (None = all vertices)
    seed_ids: Optional[List[int]] = None
    #: folded has_label() conditions on the seed set (AND of tuples)
    seed_labels: List[Tuple[str, ...]] = field(default_factory=list)
    distinct: bool = False
    as_ids: bool = False
    count_step: bool = False
    terminal_count: bool = False


# --------------------------------------------------------------- recognition
def traversal_digest(traversal) -> Tuple[str, str]:
    """(shape, digest) for a traversal BEFORE execution — same
    normalization as GraphTraversal._observe_digest, with the start
    access predicted (ids point-lookup vs full scan; the only accesses a
    compilable chain can resolve to, since property-filtered starts are
    unsupported and fall back before this matters)."""
    from janusgraph_tpu.observability.profiler import (
        shape_digest,
        traversal_shape,
    )

    plan = {"access": "ids" if traversal._start.ids else "full-scan"}
    shape = traversal_shape(
        [getattr(s, "_label", "step") for s in traversal._steps], plan
    )
    return shape, shape_digest(shape)


def recognize(traversal, terminal=None):
    """(SpilloverPlan, None) for a compilable chain, (None, reason)
    otherwise. Pure inspection — no store reads, no device work."""
    from janusgraph_tpu.core.codecs import Direction
    from janusgraph_tpu.core.elements import Vertex
    from janusgraph_tpu.core.predicates import Contain

    if getattr(traversal.source, "_sack_init", None) is not None:
        return None, "sack"
    start = traversal._start
    seed_ids = None
    if start.ids:
        seed_ids = [
            i.id if isinstance(i, Vertex) else i for i in start.ids
        ]
    seed_labels: List[Tuple[str, ...]] = []
    for key, p in traversal._pre_has:
        if key is not None:
            return None, f"seed-filter:{key}"
        if p.eq_value is not None:
            seed_labels.append((p.eq_value,))
        elif p.predicate is Contain.IN and all(
            isinstance(x, str) for x in (p.condition or ())
        ):
            seed_labels.append(tuple(p.condition))
        else:
            return None, "seed-label-predicate"
    cfg = getattr(traversal.source.graph, "config", None)
    if seed_ids is None and cfg is not None and cfg.get("query.force-index"):
        # the row path REFUSES an unindexed full scan under
        # query.force-index — spilling around the refusal would silently
        # change semantics
        return None, "force-index"
    dir_name = {
        Direction.OUT: "out", Direction.IN: "in", Direction.BOTH: "both",
    }
    hops: List[Tuple] = []
    tail: List[str] = []
    edge_tail = False
    for st in traversal._steps:
        em = getattr(st, "_expand_meta", None)
        sm = getattr(st, "_spill_meta", None)
        if em is not None:
            if tail or edge_tail:
                return None, "expansion-after-reducer"
            if em["sort_range"] is not None:
                return None, "sort-range"
            hops.append((
                dir_name[em["direction"]],
                tuple(em["labels"]) or None,
                [],
            ))
            if not em["to_vertex"]:
                # an edge expansion yields one traverser per edge — the
                # same count as the vertex expansion, so a TRAILING
                # outE/inE/bothE is compilable for counting terminals
                # only (edge objects/ids are not in the count state)
                edge_tail = True
        elif sm is not None:
            kind = sm[0]
            if kind == "hasLabel":
                if tail or edge_tail or not hops:
                    return None, "hasLabel-position"
                hops[-1][2].append(tuple(sm[1]))
            elif kind == "count":
                tail.append("count")
            elif kind in ("dedup", "id"):
                if edge_tail:
                    return None, f"edge-{kind}"
                tail.append(kind)
            else:
                return None, kind
        else:
            return None, getattr(st, "_label", "step")
    if edge_tail and not (
        tail == ["count"] or (not tail and terminal == "count")
    ):
        return None, "edge-expansion-without-count"
    distinct = as_ids = count_step = False
    for k in tail:
        if count_step:
            return None, "step-after-count"
        if k == "dedup":
            distinct = True
        elif k == "id":
            as_ids = True
        else:
            count_step = True
    shape, digest = traversal_digest(traversal)
    return SpilloverPlan(
        digest=digest, shape=shape, hops=hops, seed_ids=seed_ids,
        seed_labels=seed_labels, distinct=distinct, as_ids=as_ids,
        count_step=count_step, terminal_count=(terminal == "count"),
    ), None


# ------------------------------------------------------------- overlay view
def tx_overlay(tx) -> dict:
    """The transaction's uncommitted graph-structure delta, in graph-id
    space: added/deleted edge triples (src vid, dst vid, edge type id),
    uncommitted vertices ({vid: label id}), and removed vids. Property
    mutations are irrelevant to compilable chains (no property filters
    are supported) and are not collected."""
    from janusgraph_tpu.core.elements import Edge

    with tx._lock:
        added_rel = [r for rels in tx._added.values() for r in rels]
        deleted_rel = list(tx._deleted)
        removed = set(tx._removed_vertices)
        new_vertices = {
            vid: tx._new_vertex_labels.get(vid, 0)
            for vid, v in tx._vertex_cache.items()
            if v.is_new and not v.is_removed
        }
    added: List[Tuple[int, int, int]] = []
    seen: set = set()
    for r in added_rel:
        # new edges register under BOTH endpoint vids — dedupe by object
        if isinstance(r, Edge) and not r.is_removed and id(r) not in seen:
            seen.add(id(r))
            added.append((r.out_vertex.id, r.in_vertex.id, r.type_id))
    deleted: List[Tuple[int, int, int]] = []
    seen_ids: set = set()
    for r in deleted_rel:
        if isinstance(r, Edge) and r.id not in seen_ids:
            seen_ids.add(r.id)
            deleted.append((r.out_vertex.id, r.in_vertex.id, r.type_id))
    return {
        "added": added,
        "deleted": deleted,
        "new_vertices": new_vertices,
        "removed": removed,
        "size": len(added) + len(deleted) + len(new_vertices) + len(removed),
    }


def patched_csr(csr, overlay):
    """The snapshot with the tx overlay reconciled in: deleted edge
    INSTANCES removed from the multiset (one per tombstone — parallel
    edges with identical (src, dst, type) are count-equivalent), added
    edges appended, uncommitted vertices extending the vertex set. The
    committed snapshot is returned untouched for an empty overlay."""
    import numpy as np

    from janusgraph_tpu.olap.csr import csr_from_edges

    if not overlay["size"]:
        return csr
    vids = csr.vertex_ids
    if overlay["new_vertices"]:
        extra = np.setdiff1d(
            np.fromiter(
                overlay["new_vertices"].keys(), dtype=np.int64,
                count=len(overlay["new_vertices"]),
            ),
            vids,
        )
        vids2 = np.unique(np.concatenate([vids, extra]))
    else:
        vids2 = vids
    # labels aligned to the extended vertex set (seed has_label filters
    # must see uncommitted vertices' labels)
    labels2 = None
    if csr.labels is not None or overlay["new_vertices"]:
        labels2 = np.zeros(len(vids2), dtype=np.int64)
        if csr.labels is not None:
            pos = np.searchsorted(vids2, vids)
            labels2[pos] = csr.labels
        for vid, lid in overlay["new_vertices"].items():
            i = int(np.searchsorted(vids2, vid))
            if i < len(vids2) and vids2[i] == vid:
                labels2[i] = lid

    src_vid = np.repeat(vids, np.diff(csr.out_indptr)).astype(np.int64)
    dst_vid = vids[csr.out_dst].astype(np.int64)
    et = (
        csr.out_edge_type.astype(np.int64)
        if csr.out_edge_type is not None
        else np.zeros(len(src_vid), dtype=np.int64)
    )
    if overlay["deleted"]:
        # multiset subtraction: tokenize (src, dst, type) triples, then
        # drop the first `deleted count` instances of each token
        m = len(src_vid)
        trip = np.stack([src_vid, dst_vid, et], axis=1)
        dtrip = np.asarray(overlay["deleted"], dtype=np.int64).reshape(-1, 3)
        _, inv = np.unique(
            np.concatenate([trip, dtrip]), axis=0, return_inverse=True
        )
        etok, dtok = inv[:m], inv[m:]
        del_counts = np.bincount(dtok, minlength=int(inv.max()) + 1)
        order = np.argsort(etok, kind="stable")
        st = etok[order]
        first = np.searchsorted(st, st, side="left")
        rank = np.arange(m) - first
        keep = np.ones(m, dtype=bool)
        keep[order[rank < del_counts[st]]] = False
        src_vid, dst_vid, et = src_vid[keep], dst_vid[keep], et[keep]
    if overlay["added"]:
        a = np.asarray(overlay["added"], dtype=np.int64).reshape(-1, 3)
        src_vid = np.concatenate([src_vid, a[:, 0]])
        dst_vid = np.concatenate([dst_vid, a[:, 1]])
        et = np.concatenate([et, a[:, 2]])
    n = len(vids2)
    si = np.searchsorted(vids2, src_vid)
    di = np.searchsorted(vids2, dst_vid)
    valid = (
        (si < n) & (di < n)
        & (vids2[np.minimum(si, n - 1)] == src_vid)
        & (vids2[np.minimum(di, n - 1)] == dst_vid)
    )
    patched = csr_from_edges(
        n,
        si[valid].astype(np.int32),
        di[valid].astype(np.int32),
        edge_types=et[valid].astype(np.int32),
    )
    patched.vertex_ids = vids2
    patched.labels = labels2
    return patched


# ----------------------------------------------------------------- planner
class SpilloverPlanner:
    """Per-graph spillover state: cached snapshot + epoch, promotion set,
    and the cached single-device executor (compiled step executables
    survive across spilled queries of the same snapshot)."""

    def __init__(self, graph):
        self.graph = graph
        cfg = graph.config
        self.enabled = bool(cfg.get("computer.spillover"))
        self.min_cost_ms = float(cfg.get("computer.spillover-min-cost-ms"))
        self.min_seen = int(cfg.get("computer.spillover-min-seen"))
        self.min_hops = int(cfg.get("computer.spillover-min-hops"))
        self.max_overlay = int(cfg.get("computer.spillover-max-overlay"))
        self.max_staleness = int(cfg.get("computer.spillover-max-staleness"))
        self._lock = threading.RLock()
        self._csr = None
        self._epoch = -1
        self._tpu_ex = None
        self._promoted: Dict[str, dict] = {}

    # ------------------------------------------------------------ promotion
    def _check_promotion(self, digest: str, shape: str) -> bool:
        """Sticky promotion against the digest table's measured means.
        Call under the lock."""
        if digest in self._promoted:
            return True
        from janusgraph_tpu.observability import registry
        from janusgraph_tpu.observability.profiler import digest_table

        mean = digest_table.mean_cost_ms(digest)
        if mean is None or mean < self.min_cost_ms:
            return False
        with digest_table._lock:
            entry = digest_table._entries.get(digest)
            seen = entry["count"] if entry else 0
        if seen < self.min_seen:
            return False
        self._promoted[digest] = {
            "shape": shape, "mean_ms_at_promotion": round(mean, 3),
            "seen_at_promotion": seen, "spilled": 0, "fallbacks": 0,
        }
        with _PROMOTED_LOCK:
            _PROMOTED_GLOBAL.add(digest)
        registry.counter("olap.spillover.promotions").inc()
        # graphlint: disable=JG110 -- digest is bounded by the top-K-evicted price book (metrics.digest-top-k) that feeds promotion
        registry.set_gauge(f"olap.spillover.promoted.{digest}", 1.0)
        registry.set_gauge(
            "olap.spillover.promoted_digests", float(len(self._promoted))
        )
        from janusgraph_tpu.observability import flight_recorder

        flight_recorder.record(
            "spillover", action="promoted", digest=digest,
            mean_ms=round(mean, 3), seen=seen,
        )
        return True

    def promotion_snapshot(self) -> dict:
        with self._lock:
            return {d: dict(s) for d, s in self._promoted.items()}

    # ------------------------------------------------------------- snapshot
    def _snapshot(self):
        """The current committed-graph CSR: packed on first use, refreshed
        O(delta) from the change capture's records (zero store reads;
        olap/delta.py) while the pending overlay stays within the
        staleness bound, dropped for repack beyond it. Without a capture
        the PR 12 whole-row re-derivation (refresh_csr) remains the
        fallback. Call under the lock."""
        from janusgraph_tpu.observability import registry

        backend = self.graph.backend
        if self._csr is None:
            from janusgraph_tpu.olap.csr import load_csr_snapshot

            self._csr, self._epoch = load_csr_snapshot(self.graph)
            self._tpu_ex = None
            registry.counter("olap.spillover.packs").inc()
            registry.set_gauge("olap.spillover.staleness", 0.0)
            return self._csr
        now = backend.mutation_epoch()
        if now == self._epoch:
            registry.set_gauge("olap.spillover.staleness", 0.0)
            return self._csr
        # the freshness signal the SLO engine samples over time (the
        # PR 13 spec reads this gauge unchanged): the DELTA-OVERLAY LAG —
        # pending captured records when the capture can serve, else
        # distinct touched rows. Both dedupe repeated touches of one row
        # per (tx, row) (the tracker's per-row epoch map), so a workload
        # hammering the same rows no longer inflates staleness one epoch
        # per commit and forces spurious full repacks near the bound.
        cap = getattr(self.graph, "change_capture", None)
        lag = cap.depth_since(self._epoch) if cap is not None else None
        if lag is None:
            rows = backend.touched_count_since(self._epoch)
            lag = rows if rows is not None else (now - self._epoch)
        registry.set_gauge("olap.spillover.staleness", float(lag))
        if lag > self.max_staleness:
            # beyond the bound a full repack beats an incremental
            # refresh; THIS query falls back, the next attempt repacks
            registry.counter("olap.spillover.stale").inc()
            self._csr = None
            self._tpu_ex = None
            raise _SpillRefused("stale")
        if lag == 0:
            # property-only writes bumped the epoch but changed no
            # structure; the capture append shares the epoch lock, so a
            # zero depth at `now` proves nothing is pending
            self._epoch = now
            registry.set_gauge("olap.spillover.staleness", 0.0)
            return self._csr
        refreshed = None
        if cap is not None:
            from janusgraph_tpu.olap import delta as _delta_mod

            got = _delta_mod.overlay_since(self.graph, self._epoch)
            if got is not None:
                ov, upto = got
                registry.set_gauge(
                    "olap.delta.overlay_depth", float(ov.size)
                )
                try:
                    refreshed = (
                        _delta_mod.materialize(
                            self._csr, ov, idm=self.graph.idm,
                        )
                        if ov.size else self._csr,
                        upto if ov.size else now,
                    )
                    registry.counter(
                        "olap.spillover.delta_refreshes"
                    ).inc()
                except ValueError:
                    refreshed = None  # filtered/weighted snapshot
        if refreshed is None:
            from janusgraph_tpu.olap.csr import refresh_csr

            refreshed = refresh_csr(self.graph, self._csr, self._epoch)
        self._csr, self._epoch = refreshed
        self._tpu_ex = None
        registry.counter("olap.spillover.refreshes").inc()
        registry.set_gauge("olap.spillover.staleness", 0.0)
        return self._csr

    # ------------------------------------------------------------ execution
    def maybe_execute(self, traversal, terminal=None):
        """The planner hook body: None = run the row path. For
        ``terminal="count"`` returns the int count; otherwise the final
        traverser list."""
        steps = traversal._steps
        n_hops = sum(
            1 for s in steps if getattr(s, "_expand_meta", None) is not None
        )
        if n_hops < self.min_hops:
            return None
        plan, reason = recognize(traversal, terminal)
        if plan is None:
            # not compilable: only a PROMOTED shape's refusal is an event
            shape, digest = traversal_digest(traversal)
            with self._lock:
                hot = digest in self._promoted
            if hot:
                return self._fallback(digest, f"unsupported:{reason}")
            return None
        with self._lock:
            if not self._check_promotion(plan.digest, plan.shape):
                return None
        from janusgraph_tpu.exceptions import ServerOverloadedError
        from janusgraph_tpu.server.admission import check_olap_admission

        try:
            check_olap_admission()
        except ServerOverloadedError:
            return self._fallback(plan.digest, "brownout")
        from janusgraph_tpu.exceptions import (
            DeadlineExceededError,
            QueryError,
        )

        try:
            with self._lock:
                return self._execute_plan(traversal, plan, terminal)
        except _SpillRefused as e:
            return self._fallback(plan.digest, e.reason)
        except (QueryError, DeadlineExceededError):
            # semantic refusals (traverser budget, expired deadline) are
            # the QUERY's errors, not planner defects — the row path
            # would raise the same way, so surface them directly
            raise
        except Exception as e:  # noqa: BLE001 - fallback IS the contract:
            # a planner defect must degrade to the row walk, never fail
            # the query (the flight event + counter keep it visible)
            return self._fallback(
                plan.digest, f"error:{type(e).__name__}: {e}"[:200]
            )

    def _execute_plan(self, traversal, plan: SpilloverPlan, terminal):
        import numpy as np

        from janusgraph_tpu.core import deadline as _deadline
        from janusgraph_tpu.observability import (
            flight_recorder,
            registry,
            tracer,
        )

        _deadline.check("spillover compile")
        t0 = time.perf_counter()
        base = self._snapshot()
        packed_epoch = self._epoch
        overlay = tx_overlay(traversal.tx)
        if overlay["size"] > self.max_overlay:
            raise _SpillRefused("overlay-overflow")
        csr = patched_csr(base, overlay)
        program = self._compile(plan, csr, overlay)
        _deadline.check("spillover run")
        with tracer.span(
            "olap.spillover", digest=plan.digest, hops=len(plan.hops),
        ) as sp:
            states = self._run_program(csr, program, patched=csr is not base)
        counts = np.asarray(states["count"], dtype=np.float64)
        if counts.size and counts.max() >= float(1 << 24):
            # per-vertex traverser counts ride float32 on device — exact
            # only below 2^24; past it the row walk is the honest answer
            raise _SpillRefused("count-overflow")
        result, total = self._reduce(traversal, plan, csr, counts, terminal)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        # the spilled execution still feeds the digest table (the shape's
        # new, cheap reality) and the ambient span, like the row path
        from janusgraph_tpu.observability.profiler import digest_table

        digest_table.observe(plan.digest, plan.shape, wall_ms)
        cur = tracer.current()
        if cur is not None:
            cur.annotate(digest=plan.digest, spillover=True)
        stats = self._promoted.get(plan.digest)
        if stats is not None:
            stats["spilled"] += 1
        registry.counter("olap.spillover.spilled").inc()
        # graphlint: disable=JG110 -- digest is bounded by the top-K-evicted price book (metrics.digest-top-k) that feeds promotion
        registry.counter(f"olap.spillover.spilled.{plan.digest}").inc()
        block = {
            "digest": plan.digest,
            "shape": plan.shape,
            "hops": len(plan.hops),
            "reducer": self._reducer_name(plan, terminal),
            "overlay": {
                "added": len(overlay["added"]),
                "deleted": len(overlay["deleted"]),
                "new_vertices": len(overlay["new_vertices"]),
                "removed": len(overlay["removed"]),
            },
            "snapshot_epoch": packed_epoch,
            "wall_ms": round(wall_ms, 3),
            "result_total": total,
            "fallback": None,
        }
        olap_run = registry.last_run("olap") or {}
        run_info = {
            "spillover": block,
            "executor": olap_run.get("path"),
            "supersteps": olap_run.get("supersteps"),
        }
        registry.record_run("olap.spillover", run_info)
        flight_recorder.record(
            "spillover", action="spilled", digest=plan.digest,
            hops=len(plan.hops), overlay=overlay["size"],
            wall_ms=round(wall_ms, 3), total=total,
        )
        return result

    def _reducer_name(self, plan: SpilloverPlan, terminal) -> str:
        parts = []
        if plan.distinct:
            parts.append("dedup")
        if plan.as_ids:
            parts.append("id")
        if plan.count_step or terminal == "count":
            parts.append("count")
        return ">".join(parts) if parts else "vertices"

    def _compile(self, plan: SpilloverPlan, csr, overlay):
        import numpy as np

        from janusgraph_tpu.olap.programs.olap_traversal import (
            OLAPTraversalProgram,
            steps_from_spec,
        )

        spec = [(d, list(labels) if labels else None) for d, labels, _ in plan.hops]
        try:
            steps = steps_from_spec(self.graph, spec)
        except ValueError:
            # an edge label the schema has never seen matches nothing on
            # the row path — keep that semantics there
            raise _SpillRefused("unknown-edge-label")
        n = csr.num_vertices
        seed_mask = None
        if plan.seed_ids is not None:
            seed_mask = np.zeros(n, dtype=np.float32)
            for vid in plan.seed_ids:
                i = int(np.searchsorted(csr.vertex_ids, vid))
                if i < n and csr.vertex_ids[i] == vid and (
                    vid not in overlay["removed"]
                ):
                    # V(1, 1) seeds two traversers: the mask carries
                    # MULTIPLICITY, not membership
                    seed_mask[i] += 1.0
        if plan.seed_labels:
            lm = self._label_mask(csr, plan.seed_labels)
            seed_mask = lm if seed_mask is None else seed_mask * lm
        if overlay["removed"]:
            rm = np.asarray(sorted(overlay["removed"]), dtype=np.int64)
            pos = np.searchsorted(csr.vertex_ids, rm)
            ok = (pos < n) & (csr.vertex_ids[np.minimum(pos, n - 1)] == rm)
            if seed_mask is None:
                seed_mask = np.ones(n, dtype=np.float32)
            seed_mask[pos[ok]] = 0.0
        step_masks = None
        if any(vlabels for _, _, vlabels in plan.hops):
            cols = [
                self._label_mask(csr, vlabels)
                if vlabels
                else np.ones(n, dtype=np.float32)
                for _, _, vlabels in plan.hops
            ]
            step_masks = np.stack(cols, axis=1)
        return OLAPTraversalProgram(
            steps, seed_mask=seed_mask, step_masks=step_masks
        )

    def _label_mask(self, csr, label_groups):
        """AND over has_label() groups: each group is an OR of vertex
        label NAMES (unknown names match nothing, like the row filter)."""
        import numpy as np

        n = csr.num_vertices
        if csr.labels is None:
            raise _SpillRefused("no-label-column")
        mask = np.ones(n, dtype=np.float32)
        for group in label_groups:
            ids = []
            for name in group:
                el = self.graph.schema_cache.get_by_name(name)
                if el is not None:
                    ids.append(el.id)
            m = (
                np.isin(csr.labels, np.asarray(ids, dtype=np.int64))
                if ids
                else np.zeros(n, dtype=bool)
            )
            mask *= m.astype(np.float32)
        return mask

    def _run_program(self, csr, program, patched: bool):
        """Route like graph.compute(): the configured executor, with
        computer.sharded-auto sending multi-device processes to the
        sharded executor. The single-device executor is CACHED per
        snapshot so compiled step executables survive across spilled
        queries (patched-snapshot runs use a throwaway executor — the
        patch is per transaction)."""
        cfg = self.graph.config
        executor = cfg.get("computer.executor")
        if executor == "tpu" and cfg.get("computer.sharded-auto"):
            try:
                import jax

                ndev = len(jax.devices())
            except Exception:  # noqa: BLE001 - jax may be uninitialized
                ndev = 1
            if ndev > 1 and getattr(program, "sharded_compatible", True):
                executor = "sharded"
        if executor == "tpu":
            from janusgraph_tpu.olap.tpu_executor import TPUExecutor

            if patched:
                return TPUExecutor(csr).run(program)
            if self._tpu_ex is None or self._tpu_ex.csr is not csr:
                self._tpu_ex = TPUExecutor(csr)
            return self._tpu_ex.run(program)
        from janusgraph_tpu.olap.computer import run_on

        kwargs = {}
        if executor == "sharded":
            kwargs = {
                "exchange": cfg.get("computer.exchange"),
                "agg": cfg.get("computer.agg"),
                "frontier_tier_growth": cfg.get(
                    "computer.frontier-tier-growth"
                ),
            }
        return run_on(csr, program, executor, **kwargs)

    def _reduce(self, traversal, plan: SpilloverPlan, csr, counts, terminal):
        """Fold the per-vertex traverser counts into the chain's output:
        (result, total). ``result`` is an int for the count() terminal,
        else the final traverser list."""
        import numpy as np

        from janusgraph_tpu.core.traversal import Traverser

        if plan.distinct:
            mult = (counts > 0).astype(np.int64)
        else:
            mult = np.rint(counts).astype(np.int64)
        total = int(mult.sum())
        if plan.count_step:
            # count as a STEP yields one int traverser; the count()
            # TERMINAL over it is its len (= 1), like the row path
            if terminal == "count":
                return 1, total
            return [Traverser(total)], total
        if terminal == "count":
            return total, total
        cap = getattr(self.graph, "_max_traversers", 0)
        if cap and total > cap:
            # the row walk would have refused this frontier size — the
            # spilled path must not bypass the budget on MATERIALIZED
            # output (count terminals never materialize)
            from janusgraph_tpu.exceptions import QueryError

            raise QueryError(
                f"traverser count {total} exceeds query.max-traversers "
                f"({cap}) in spilled traversal"
            )
        idxs = np.nonzero(mult)[0]
        out: List[Traverser] = []
        if plan.as_ids:
            for i in idxs:
                vid = int(csr.vertex_ids[i])
                out.extend(Traverser(vid) for _ in range(int(mult[i])))
            return out, total
        tx = traversal.tx
        for i in idxs:
            v = _vertex_handle(tx, int(csr.vertex_ids[i]))
            if v is None:
                continue
            out.extend(Traverser(v) for _ in range(int(mult[i])))
        return out, total

    # ------------------------------------------------------------- fallback
    def _fallback(self, digest: str, reason: str):
        from janusgraph_tpu.observability import flight_recorder, registry

        registry.counter("olap.spillover.fallback").inc()
        head = reason.split(":", 1)[0]
        # graphlint: disable=JG110 -- head is the fixed refusal-reason vocabulary (unsupported/overlay/stale/brownout/overflow/error)
        registry.counter(f"olap.spillover.fallback.{head}").inc()
        with self._lock:
            stats = self._promoted.get(digest)
            if stats is not None:
                stats["fallbacks"] += 1
        flight_recorder.record(
            "spillover_fallback", digest=digest, reason=reason,
        )
        registry.record_run("olap.spillover", {
            "spillover": {"digest": digest, "fallback": reason},
        })
        return None


def _vertex_handle(tx, vid: int):
    """A Vertex handle for a vid the snapshot (or tx overlay) proved
    alive — tx.get_vertex minus the per-vid existence read, sharing the
    tx vertex cache so spilled results alias the row path's handles."""
    from janusgraph_tpu.core.elements import LifeCycle, Vertex

    with tx._lock:
        v = tx._vertex_cache.get(vid)
        if v is not None:
            return None if v.is_removed else v
        if vid in tx._removed_vertices:
            return None
        v = Vertex(vid, tx, LifeCycle.LOADED)
        tx._vertex_cache[vid] = v
    return v


# ------------------------------------------------------------------ the hook
def try_spill(traversal, terminal=None):
    """GraphTraversal's planner hook: spilled result, or None to run the
    row-by-row path. Never raises planner-internal errors (fallback is
    the contract); QueryError from budget enforcement propagates like
    the row path's own."""
    source = getattr(traversal, "source", None)
    graph = getattr(source, "graph", None) if source is not None else None
    planner = getattr(graph, "spillover_planner", None)
    if planner is None or not planner.enabled:
        return None
    start = traversal._start
    if start is None or type(start).__name__ != "_start_vertices":
        return None
    return planner.maybe_execute(traversal, terminal)
