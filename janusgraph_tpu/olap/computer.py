"""GraphComputer facade: the user-facing OLAP entry point.

Capability parity with the reference's computer API
(reference: graphdb/olap/computer/FulgoraGraphComputer.java:74 — submit()
returning a result with vertex state + memory; GraphFilter via edges()/
vertices()): `graph.compute()` bulk-loads the CSR snapshot, runs the chosen
executor, and hands back state arrays with write-back support.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

from janusgraph_tpu.olap.csr import CSRGraph, load_csr
from janusgraph_tpu.olap.vertex_program import VertexProgram


@dataclass
class ComputerResult:
    states: Dict[str, np.ndarray]
    csr: CSRGraph
    graph: object = None
    #: map-reduce results keyed by each job's memory_key (reference:
    #: FulgoraMemory holding MapReduce side-effect keys)
    memory: Dict[str, object] = field(default_factory=dict)
    #: the executor's run record (registry.last_run("olap") shape) plus
    #: the submit() routing decision under "routing"
    run_info: Dict[str, object] = field(default_factory=dict)
    #: the program that produced `states` (path()/select() terminals)
    program: object = None
    #: name of path position 0 for select() (compute().traverse(source_as=))
    source_as: object = None

    def _path_index(self):
        """Memoized reverse-adjacency index: paths() then select() on the
        same result must not pay the O(E log E) per-step sorts twice."""
        from janusgraph_tpu.olap.programs.olap_traversal import (
            build_path_index,
        )

        idx = getattr(self, "_path_index_cache", None)
        if idx is None:
            idx = build_path_index(self.csr, self.program)
            object.__setattr__(self, "_path_index_cache", idx)
        return idx

    def paths(self, limit=None):
        """Enumerate traverser paths (tuples of vertex ids, seed first) —
        requires compute().traverse(..., paths=True). Lazy generator;
        pass `limit` on dense graphs (path counts explode — the device
        count sum prices the enumeration: states['count'].sum())."""
        from janusgraph_tpu.olap.programs.olap_traversal import (
            enumerate_paths,
        )

        if "reach" not in self.states:
            raise ValueError(
                "no reach masks recorded — run "
                "compute().traverse(..., paths=True)"
            )
        # the bound method, NOT called: the generator resolves it on first
        # iteration, so un-iterated paths() costs nothing
        return enumerate_paths(
            self.csr, self.program, self.states, limit,
            path_index=self._path_index,
        )

    def select(self, *names, limit=None):
        """Project as()-labeled path positions (TinkerPop SelectStep shape):
        yields {name: vertex_id} dicts. Label steps via 4-tuple spec items
        ('out', labels, filters, 'b'); name the source with
        traverse(source_as='a')."""
        from janusgraph_tpu.olap.programs.olap_traversal import select_paths

        if "reach" not in self.states:
            raise ValueError(
                "no reach masks recorded — run "
                "compute().traverse(..., paths=True)"
            )
        return select_paths(
            self.csr, self.program, self.states, names,
            source_as=self.source_as, limit=limit,
            path_index=self._path_index,
        )

    def value(self, key: str, vertex_id: int) -> float:
        return float(self.states[key][self.csr.index_of(vertex_id)])

    def by_vertex(self, key: str) -> Dict[int, float]:
        arr = self.states[key]
        return {int(v): float(arr[i]) for i, v in enumerate(self.csr.vertex_ids)}

    def write_back(self, keys: Optional[Sequence[str]] = None) -> None:
        from janusgraph_tpu.olap.tpu_executor import write_back

        cfg = getattr(self.graph, "config", None)
        batch = cfg.get("computer.write-back-batch") if cfg else 10_000
        write_back(self.graph, self.csr, self.states, keys, batch=batch)


class GraphComputer:
    """graph.compute() builder (reference: JanusGraphComputer). Executor
    kind, aggregation strategy, sync cadence and checkpointing default to
    the graph's registered config (computer.* options)."""

    def __init__(self, graph, executor: str = None):
        self.graph = graph
        cfg = getattr(graph, "config", None)
        if executor is None:
            executor = cfg.get("computer.executor") if cfg else "tpu"
        self.executor_kind = executor
        self._edge_labels: Optional[Sequence[str]] = None
        self._vertex_labels: Optional[Sequence[str]] = None
        self._property_keys: Sequence[str] = ()
        self._weight_key: Optional[str] = None
        self._program: Optional[VertexProgram] = None
        self._map_reduces: list = []

    def edges(self, *labels: str) -> "GraphComputer":
        """GraphFilter on edge labels (reference: GraphComputer.edges)."""
        self._edge_labels = labels
        return self

    def vertices(self, *labels: str) -> "GraphComputer":
        """GraphFilter on vertex labels (reference: GraphComputer.vertices)."""
        self._vertex_labels = labels
        return self

    def map_reduce(self, mr) -> "GraphComputer":
        """Add a MapReduce job over the final vertex state (reference:
        FulgoraGraphComputer.mapReduce)."""
        self._map_reduces.append(mr)
        return self

    def properties(self, *keys: str) -> "GraphComputer":
        self._property_keys = keys
        return self

    def weight(self, key: str) -> "GraphComputer":
        self._weight_key = key
        return self

    def program(self, p: VertexProgram) -> "GraphComputer":
        self._program = p
        # an explicit program supersedes any earlier traverse() shortcut —
        # submit() must not rebuild an OLAP-traversal program over it
        self._traverse_args = None
        return self

    def traverse(
        self, *spec, seed_filters=None, paths=False, source_as=None,
        sack=None, sack_init=None,
    ) -> "GraphComputer":
        """OLAP traversal shortcut (the TraversalVertexProgram analogue):
        compute().traverse(("out", ["knows"]), ("in", None)).submit() counts
        traversers per vertex; result.states["count"].sum() is the terminal
        count (reference: BASELINE config #5). Spec items may carry has()-
        filters — ("out", ["knows"], [("age", Cmp.GREATER_THAN, 30)]) — and
        `seed_filters` restricts the start set; filter masks are built from
        the CSR snapshot at submit() (build_olap_traversal).

        `paths=True` additionally records per-step reach masks device-side
        so the result supports `.paths()` / `.select()` (host traverser
        bookkeeping; olap_traversal.enumerate_paths). `source_as` names
        path position 0 for select().

        `sack="sum"|"mult"` carries a per-traverser sack folded with the
        edge weight each hop (withSack().sack(op).by(weight)); pair with
        .weight(key) so the CSR ships the weight column. result.states
        ["sack"][v] = total sack mass of the traversers at v."""
        # defer program construction to submit(): filter masks need the
        # loaded CSR's property columns
        self._traverse_args = (
            spec, seed_filters, paths, source_as, sack, sack_init,
        )
        self._program = None
        return self

    def submit(self) -> ComputerResult:
        """Load the CSR snapshot, run the program, wrap the result — the
        whole pipeline under an `olap.submit` span (children: the
        `olap.load_csr` snapshot load, the executor's `olap.run` with its
        per-superstep spans, and one `olap.map_reduce` per job)."""
        from janusgraph_tpu.observability import tracer
        from janusgraph_tpu.server import admission as _admission

        # brownout rung 2 (server/admission.py): when the serving path is
        # under sustained overload, analytical jobs — the biggest cost
        # multiplier a query can trigger — are refused so OLTP goodput
        # survives; a no-op whenever no server runs in this process
        _admission.check_olap_admission()
        with tracer.span("olap.submit", executor=self.executor_kind) as sp:
            return self._submit(sp)

    def _submit(self, sp) -> ComputerResult:
        from janusgraph_tpu.observability import tracer

        property_keys = self._property_keys
        traverse_args = getattr(self, "_traverse_args", None)
        if traverse_args is not None:
            # filters reference property names: make sure the snapshot
            # loads those columns
            from janusgraph_tpu.olap.programs.olap_traversal import (
                _parse_filters,
                steps_from_spec,
            )

            spec, seed_filters = traverse_args[0], traverse_args[1]
            fkeys = {f.key for f in _parse_filters(seed_filters)}
            for st in steps_from_spec(self.graph, spec):
                fkeys.update(f.key for f in st.filters)
            property_keys = tuple(set(property_keys or ()) | fkeys)
        assert (
            self._program is not None or traverse_args is not None
        ), "program() not set"
        cfg = getattr(self.graph, "config", None)
        with tracer.span("olap.load_csr") as ls:
            # distributed CSR loading (storage.distributed-load-workers):
            # N worker processes scan disjoint storage-partition ranges of
            # a SHARED backend and the parent merges once — the raw scan
            # carries no property/weight/filter columns, so any of those
            # falls back to the in-process loader
            workers = int(cfg.get("storage.distributed-load-workers") or 0) if cfg else 0
            plain = not (
                property_keys or self._weight_key
                or self._edge_labels or self._vertex_labels
            )
            backend = cfg.get("storage.backend") if cfg else None
            # warm delta snapshot (computer.delta; olap/delta.py): plain
            # snapshots reuse the cached base CSR — a warm submit skips
            # the store scan entirely; pending writes arrive as an
            # overlay consumed fused (small) or folded into fresh arrays
            # with zero store reads (large)
            delta_snap = delta_view = None
            if plain and cfg is not None and cfg.get("computer.delta") and (
                workers <= 1
            ):
                from janusgraph_tpu.olap import delta as _delta_mod

                delta_snap = _delta_mod.get_snapshot(self.graph)
            if delta_snap is not None:
                csr, delta_view, dinfo = delta_snap.acquire()
                ls.annotate(
                    delta_path=dinfo["path"],
                    overlay=dinfo.get("overlay", 0),
                )
            elif workers > 1 and plain and backend in ("remote", "local"):
                from janusgraph_tpu.olap.distributed_load import (
                    distributed_load_csr,
                )

                csr = distributed_load_csr(
                    dict(cfg.local), num_workers=workers,
                    timeout_s=float(
                        cfg.get("storage.distributed-load-timeout-s")
                    ),
                )
                ls.annotate(distributed_workers=workers)
            else:
                csr = load_csr(
                    self.graph,
                    edge_labels=self._edge_labels,
                    vertex_labels=self._vertex_labels,
                    property_keys=property_keys,
                    weight_key=self._weight_key,
                )
            ls.annotate(
                num_vertices=csr.num_vertices, num_edges=csr.num_edges
            )
        if traverse_args is not None:
            from janusgraph_tpu.olap.programs.olap_traversal import (
                build_olap_traversal,
            )

            spec, seed_filters, want_paths, source_as, sack, sack_init = (
                traverse_args
            )
            self._program = build_olap_traversal(
                self.graph, csr, spec, seed_filters=seed_filters,
                record_reach=want_paths, sack=sack, sack_init=sack_init,
            )
        # ---- executor routing (computer.sharded-auto, default on): with
        # more than one visible device, the default 'tpu' submit routes to
        # the sharded executor — multi-chip is the default fast path. The
        # routing decision rides run_info["routing"]; a routed run that
        # fails (e.g. collectives unavailable on this backend) falls back
        # to the single-device executor instead of failing the submit.
        executor_kind = self.executor_kind
        routing = {"requested": self.executor_kind,
                   "routed": self.executor_kind, "reason": "explicit"}
        if self.executor_kind == "tpu" and not getattr(
            self, "_no_autoroute", False
        ) and (
            cfg is None or cfg.get("computer.sharded-auto")
        ):
            try:
                import jax

                ndev = len(jax.devices())
            except Exception:
                ndev = 1
            if ndev > 1 and getattr(
                self._program, "sharded_compatible", True
            ):
                executor_kind = "sharded"
                routing = {
                    "requested": self.executor_kind, "routed": "sharded",
                    "reason": f"sharded-auto: mesh of {ndev} devices",
                }
            else:
                routing["reason"] = (
                    "single device" if ndev <= 1 else "sddmm program"
                )
        run_kwargs = {}
        if cfg is not None and executor_kind == "sharded":
            run_kwargs = {
                "sync_every": cfg.get("computer.sync-every"),
                "checkpoint_every": (
                    cfg.get("computer.shard-checkpoint-every")
                    or cfg.get("computer.checkpoint-every")
                ),
                "checkpoint_path": cfg.get("computer.checkpoint-path") or None,
                "shard_checkpoint_dir": (
                    cfg.get("computer.shard-checkpoint-path") or None
                ),
                "frontier": cfg.get("computer.frontier"),
                "exchange": cfg.get("computer.exchange"),
                "agg": cfg.get("computer.agg"),
                "frontier_tier_growth": cfg.get(
                    "computer.frontier-tier-growth"
                ),
                "shard_measure": cfg.get("computer.shard-measure"),
                "features_dim_tier": cfg.get("computer.features-dim-tier"),
                "features_native_matmul": cfg.get(
                    "computer.features-native-matmul"
                ),
            }
        if cfg is not None and executor_kind == "tpu":
            run_kwargs = {
                "strategy": cfg.get("computer.strategy"),
                "ell_max_capacity": cfg.get("computer.ell-max-capacity"),
                "sync_every": cfg.get("computer.sync-every"),
                "checkpoint_every": cfg.get("computer.checkpoint-every"),
                "checkpoint_path": cfg.get("computer.checkpoint-path") or None,
                "frontier": cfg.get("computer.frontier"),
                "ell_auto_bytes": cfg.get("computer.ell-auto-budget-bytes"),
                "ell_auto_pad": cfg.get("computer.ell-auto-pad"),
                "channel_cache_size": cfg.get("computer.channel-cache-size"),
                "frontier_cc_min_edges": cfg.get(
                    "computer.frontier-cc-min-edges"
                ),
                "frontier_f_min": cfg.get("computer.frontier-f-min"),
                "frontier_e_min": cfg.get("computer.frontier-e-min"),
                "frontier_tier_growth": cfg.get(
                    "computer.frontier-tier-growth"
                ),
                "autotune": cfg.get("computer.autotune"),
                "hub_cutoff": cfg.get("computer.autotune-hub-cutoff"),
                "tail_chunk": cfg.get("computer.autotune-tail-chunk"),
                "autotune_min_gain": cfg.get("computer.autotune-min-gain"),
                "autotune_max_tiers": cfg.get("computer.autotune-max-tiers"),
                "autotune_persist": cfg.get("computer.autotune-persist"),
                "features_dim_tier": cfg.get("computer.features-dim-tier"),
                "features_native_matmul": cfg.get(
                    "computer.features-native-matmul"
                ),
            }
        if cfg is not None and executor_kind == "cpu":
            run_kwargs = {
                "checkpoint_every": cfg.get("computer.checkpoint-every"),
                "checkpoint_path": cfg.get("computer.checkpoint-path") or None,
                "shard_checkpoint_dir": (
                    cfg.get("computer.shard-checkpoint-path") or None
                ),
                "checkpoint_shards": cfg.get(
                    "computer.shard-checkpoint-shards"
                ),
                "features_dim_tier": cfg.get("computer.features-dim-tier"),
                "features_native_matmul": cfg.get(
                    "computer.features-native-matmul"
                ),
            }
            # the CPU oracle writes the sharded format only when a slice
            # count is configured — a bare shard-checkpoint-path on a
            # single-device run still means the single-file format
            if not run_kwargs["checkpoint_shards"]:
                run_kwargs["shard_checkpoint_dir"] = None
        # chaos wiring: a graph opened with storage.faults.enabled carries
        # a FaultPlan; its superstep-preemption hook rides into the
        # executors, where checkpoint auto-resume absorbs it. The sharded
        # executor gets the mesh-aware hook (shard preemption, collective
        # timeout, halo drop, straggler skew) — cross-shard auto-resume
        # rolls every shard back to the last complete manifest.
        plan = getattr(self.graph, "fault_plan", None)
        if executor_kind in ("tpu", "cpu", "sharded"):
            if plan is not None:
                run_kwargs["fault_hook"] = (
                    plan.sharded_hook
                    if executor_kind == "sharded"
                    else plan.olap_hook
                )
            if cfg is not None:
                run_kwargs["resume_attempts"] = cfg.get(
                    "computer.resume-attempts"
                )
        sp.annotate(program=type(self._program).__name__)
        # ---- pending-overlay consumption: small overlays ride into the
        # single-device executor FUSED (base pack untouched, delta lanes
        # merged in the superstep); anything else — sharded runs, typed-
        # channel programs, oversized lanes — folds into fresh arrays
        # first (zero store reads either way)
        if delta_view is not None:
            from janusgraph_tpu.olap import delta as _delta_mod

            und = bool(getattr(self._program, "undirected", False))
            fuse = (
                executor_kind == "tpu"
                and _delta_mod.program_delta_compatible(self._program)
                and csr.in_edge_weight is None
                and delta_view.lanes(und) is not None
            )
            if fuse:
                run_kwargs["delta"] = delta_view
                sp.annotate(delta="fused", overlay=delta_view.depth)
            else:
                csr = _delta_mod.materialize(
                    csr, delta_view.overlay,
                    idm=getattr(self.graph, "idm", None),
                )
                if delta_snap is not None and (
                    delta_view.upto_epoch is not None
                ):
                    delta_snap.adopt(csr, delta_view.upto_epoch)
                sp.annotate(delta="materialized", overlay=delta_view.depth)
                delta_view = None
        from janusgraph_tpu.observability import registry

        # warm-submit executor cache (the PR 14 REMAINING): when this
        # submit runs over the delta snapshot's CURRENT base pack, the
        # executor — device-resident packs and compiled executables
        # included — is cached on the snapshot and reused next submit,
        # invalidated by any compaction/adopt (generation bump)
        if delta_snap is not None and csr is delta_snap.csr:
            run_kwargs["executor_cache"] = delta_snap
        try:
            states = run_on(csr, self._program, executor_kind, **run_kwargs)
        except Exception as e:
            if routing["routed"] == executor_kind == "sharded" and (
                self.executor_kind != "sharded"
            ):
                # auto-routing must never make a working submit fail:
                # rebuild the single-device kwargs and retry there
                from janusgraph_tpu.observability import flight_recorder

                routing["routed"] = "tpu"
                routing["fallback"] = f"{type(e).__name__}: {e}"[:200]
                flight_recorder.record(
                    "sharded_auto_fallback",
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                self._no_autoroute = True
                try:
                    result = self._submit(sp)
                finally:
                    self._no_autoroute = False
                # preserve the fallback story for callers and dashboards
                result.run_info["routing"] = routing
                registry.record_run("olap.routing", routing)
                return result
            raise
        if run_kwargs.get("delta") is not None:
            # fused-run results cover [base ++ new vertices] with removed
            # slots inert: compact to the surviving set so value()/
            # by_vertex()/write_back see exactly the live graph
            from janusgraph_tpu.olap import delta as _delta_mod

            states, csr = _delta_mod.compact_result(delta_view, states)
        if delta_snap is not None:
            # compaction is off the superstep path: fold the overlay into
            # the base pack AFTER the run when it crossed the threshold
            delta_snap.maybe_compact()
        routing["executor"] = executor_kind
        registry.record_run("olap.routing", routing)
        run_info = dict(registry.last_run("olap") or {})
        run_info["routing"] = routing
        memory = {}
        if self._map_reduces:
            from janusgraph_tpu.olap.mapreduce import run_map_reduce

            for mr in self._map_reduces:
                with tracer.span(
                    "olap.map_reduce", job=type(mr).__name__,
                    key=mr.memory_key,
                ):
                    memory[mr.memory_key] = run_map_reduce(mr, states, csr)
        return ComputerResult(
            states=states, csr=csr, graph=self.graph, memory=memory,
            run_info=run_info,
            program=self._program,
            source_as=(
                traverse_args[3] if traverse_args is not None else None
            ),
        )


def run_on(
    csr: CSRGraph,
    program: VertexProgram,
    executor: str = "tpu",
    strategy: str = "auto",
    ell_max_capacity: int = None,
    sync_every: int = 1,
    checkpoint_every: int = 0,
    checkpoint_path: str = None,
    frontier: str = "auto",
    ell_auto_bytes: int = None,
    ell_auto_pad: float = None,
    channel_cache_size: int = None,
    frontier_cc_min_edges: int = None,
    frontier_f_min: int = None,
    frontier_e_min: int = None,
    frontier_tier_growth: int = None,
    exchange: str = "a2a",
    agg: str = "ell",
    shard_measure: bool = None,
    fault_hook=None,
    resume_attempts: int = 3,
    autotune: bool = None,
    hub_cutoff: int = None,
    tail_chunk: int = None,
    autotune_min_gain: float = None,
    autotune_max_tiers: int = None,
    autotune_persist: bool = None,
    features_dim_tier: int = None,
    features_native_matmul: bool = None,
    cpu_strategy: str = "scalar",
    shard_checkpoint_dir: str = None,
    checkpoint_shards: int = 0,
    delta=None,
    executor_cache=None,
):
    # dense-feature tier program configuration (computer.features-*):
    # applied here so EVERY executor sees the same padded lane tier and
    # matmul flavor (TPUExecutor re-applies the tier for direct callers)
    if features_dim_tier and hasattr(program, "set_dim_tier"):
        program.set_dim_tier(features_dim_tier)
    if features_native_matmul is not None and hasattr(
        program, "set_native_matmul"
    ):
        program.set_native_matmul(features_native_matmul)
    if executor == "cpu":
        from janusgraph_tpu.olap.cpu_executor import CPUExecutor

        ex = None
        cache_key = ("cpu", cpu_strategy)
        if executor_cache is not None:
            ex = executor_cache.cached_executor(cache_key)
        if ex is None:
            ex = CPUExecutor(csr, strategy=cpu_strategy, delta=delta)
            if executor_cache is not None:
                executor_cache.store_executor(cache_key, ex, csr)
        else:
            ex.set_delta(delta)
        return ex.run(
            program,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            fault_hook=fault_hook,
            resume_attempts=resume_attempts,
            shard_checkpoint_dir=shard_checkpoint_dir,
            checkpoint_shards=checkpoint_shards,
        )
    if executor == "sharded":
        if delta is not None:
            raise ValueError(
                "the sharded executor consumes MATERIALIZED delta "
                "snapshots (route_overlay + per-shard rebuild) — fold "
                "the overlay with olap/delta.materialize first"
            )
        from janusgraph_tpu.parallel import ShardedExecutor

        return ShardedExecutor(
            csr, exchange=exchange, agg=agg,
            frontier_tier_growth=frontier_tier_growth,
            shard_measure=shard_measure,
        ).run(
            program,
            sync_every=sync_every,
            checkpoint_every=checkpoint_every,
            checkpoint_path=checkpoint_path,
            frontier=frontier,
            fault_hook=fault_hook,
            resume_attempts=resume_attempts,
            shard_checkpoint_dir=shard_checkpoint_dir,
        )
    if executor == "tpu":
        from janusgraph_tpu.olap.tpu_executor import TPUExecutor

        ctor_kwargs = dict(
            strategy=strategy,
            ell_max_capacity=ell_max_capacity,
            frontier=frontier,
            ell_auto_bytes=ell_auto_bytes,
            ell_auto_pad=ell_auto_pad,
            channel_cache_size=channel_cache_size,
            frontier_cc_min_edges=frontier_cc_min_edges,
            frontier_f_min=frontier_f_min,
            frontier_e_min=frontier_e_min,
            frontier_tier_growth=frontier_tier_growth,
            autotune=autotune,
            hub_cutoff=hub_cutoff,
            tail_chunk=tail_chunk,
            autotune_min_gain=autotune_min_gain,
            autotune_max_tiers=autotune_max_tiers,
            autotune_persist=autotune_persist,
            features_dim_tier=features_dim_tier,
        )
        ex = None
        # the overlay is NOT part of the key: a cached executor swaps it
        # per submit (set_delta), and its compiled executables are keyed
        # by lane signature internally
        cache_key = ("tpu",) + tuple(sorted(ctor_kwargs.items()))
        if executor_cache is not None:
            ex = executor_cache.cached_executor(cache_key)
        if ex is None:
            ex = TPUExecutor(csr, delta=delta, **ctor_kwargs)
            if executor_cache is not None:
                executor_cache.store_executor(cache_key, ex, csr)
        else:
            ex.set_delta(delta)
        return ex.run(
            program,
            sync_every=sync_every,
            checkpoint_every=checkpoint_every,
            checkpoint_path=checkpoint_path,
            fault_hook=fault_hook,
            resume_attempts=resume_attempts,
        )
    raise ValueError(f"unknown executor {executor!r}")
