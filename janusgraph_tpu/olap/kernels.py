"""TPU aggregation kernels for the BSP superstep.

The superstep's hot op is `combine({msg(src) for (src,dst) edges}) by dst` —
the reference runs it as NonBlockingHashMapLong insert-with-combiner per
message (reference: FulgoraVertexMemory.java:91-99); the straightforward XLA
translation is gather + `segment_sum`, whose scatter-add lowering serializes
poorly on TPU. Two TPU-native alternatives here:

1. **Degree-bucketed ELL** (`ELLPack` / `ell_aggregate`): in-edges are packed
   per destination into power-of-two-capacity row buckets (ELLPACK layout).
   Aggregation becomes gather + dense axis-1 reduction — no scatter at all,
   every monoid (sum/min/max) supported, padding overhead < 2× by the
   power-of-two bucketing. This is the default device strategy.

2. **Degree-bucketed HYBRID** (`HybridPack` / `hybrid_aggregate`): the
   ELL pack's power-of-two bucket rounding moves 1.4-1.5x the edge count in
   sentinel padding on heavy-tailed graphs (every bench round since r01).
   The hybrid keeps an ELL-shaped torso packed at EXACT degree widths
   (zero padding) for vertices at or below a degree cutoff, and routes hub
   vertices through a chunked CSR tail: contiguous `tail_chunk`-wide slices
   of the destination-sorted edge array, folded into per-row partial tables.
   Results are BITWISE-IDENTICAL to the pure-ELL path because both reduce
   through the same fixed adjacent-pair tree (`tree_reduce`): a width-2^k
   ELL row's reduction tree decomposes exactly into the per-chunk subtrees
   plus the partial-table fold, and in-kernel identity padding reproduces
   the sentinel slots leaf-for-leaf.

3. **Pallas sorted-segment-sum** (`pallas_sorted_segment_sum`): edges are
   already destination-sorted (CSR); host-side alignment pads each output
   tile's edge range to whole blocks, so each edge block accumulates into
   exactly one output tile. The kernel one-hot-expands local segment ids and
   reduces on the MXU/VPU, revisiting the same output block across grid
   steps (zeroed on first touch). SUM monoid; used for PageRank-shaped
   programs.

All are built once per (graph, orientation) and reused across supersteps.
The aggregation entry points take the array module (`jnp` or plain numpy)
as their first argument, so the CPU oracle can run the identical pack
arithmetic for cross-executor bitwise checks.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    apply_edge_transform,
)


# --------------------------------------------------------------------------
# Degree-bucketed ELL packing
# --------------------------------------------------------------------------

def fill_ell_rows(cap, starts_r, degs_r, src32, w32, idx, wmat, valid):
    """Fill one ELL bucket's (rows, cap) matrices in place — native fast
    path with a numpy fallback. Callers pre-fill idx with the sentinel and
    wmat/valid with zeros; wmat/valid are None for unweighted packs (the
    sentinel slot alone provides the monoid identity on device)."""
    from janusgraph_tpu import native

    if native.ell_fill(cap, starts_r, degs_r, src32, w32, idx, wmat, valid):
        return
    total = int(np.asarray(degs_r).sum())
    if not total:
        return
    degs_r = np.asarray(degs_r, dtype=np.int64)
    starts_r = np.asarray(starts_r, dtype=np.int64)
    rows = len(starts_r)
    row_ids = np.repeat(np.arange(rows), degs_r)
    col_ids = np.arange(total) - np.repeat(
        np.cumsum(degs_r) - degs_r, degs_r
    )
    edge_pos = np.repeat(starts_r, degs_r) + col_ids
    idx[row_ids, col_ids] = src32[edge_pos]
    if valid is not None:
        valid[row_ids, col_ids] = 1.0
    if wmat is not None:
        wmat[row_ids, col_ids] = w32[edge_pos] if w32 is not None else 1.0


def split_rows(
    members: np.ndarray,
    deg_m: np.ndarray,
    starts_m: np.ndarray,
    cap: int,
):
    """Row-split supernode edge ranges into chunks of at most `cap` edges.

    Returns (starts, degs, rowseg): one entry per row; rowseg maps each row
    to its owner's slot index (position within `members`). Vertices with
    degree <= cap keep one row. This bounds ELL padding at < 2× regardless
    of max degree — a supernode costs ceil(d/cap) dense rows, not a bucket
    padded to the global max degree (supernodes: SURVEY.md §5.7).
    """
    n_rows = np.maximum(1, -(-deg_m // cap)).astype(np.int64)
    total = int(n_rows.sum())
    rowseg = np.repeat(np.arange(len(members), dtype=np.int64), n_rows)
    chunk = (
        np.arange(total, dtype=np.int64)
        - np.repeat(np.cumsum(n_rows) - n_rows, n_rows)
    )
    starts = np.repeat(starts_m, n_rows) + chunk * cap
    degs = np.minimum(cap, np.repeat(deg_m, n_rows) - chunk * cap)
    degs = np.maximum(degs, 0)
    return starts, degs, rowseg


class ELLPack:
    """Host-side ELLPACK layout of an edge list grouped by destination.

    For each power-of-two capacity bucket c: the destinations whose in-degree
    d satisfies prev_c < d <= c, with a (rows, c) matrix of source indices
    (padded with a sentinel slot) and a (rows, c) weight/validity matrix.
    Destinations with degree > max_capacity are ROW-SPLIT into ceil(d/cap)
    rows of the top bucket; `rowseg` then folds row partials into one slot
    per destination with a small (rows-sized, not edges-sized) segment
    reduction.

    Bucket tuple: (idx, wmat, valid, rowseg, num_slots); rowseg is None when
    rows == slots (no split rows in that bucket).

    `sentinel` is index `n` — callers extend the per-vertex message vector by
    one identity element so padded slots read the monoid identity.
    """

    def __init__(
        self,
        src: np.ndarray,
        dst: np.ndarray,
        weight: Optional[np.ndarray],
        num_vertices: int,
        max_capacity: int = 1 << 14,
    ):
        n = num_vertices
        self.num_vertices = n
        self.sentinel = n
        self.has_weight = weight is not None
        order = np.argsort(dst, kind="stable")
        src = np.asarray(src, dtype=np.int64)[order]
        dst = np.asarray(dst, dtype=np.int64)[order]
        w = (
            np.asarray(weight, dtype=np.float32)[order]
            if weight is not None
            else None
        )
        deg = np.bincount(dst, minlength=n).astype(np.int64)
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=indptr[1:])

        # bucket capacity per vertex: next power of two >= degree (min 1),
        # clamped to max_capacity (larger degrees row-split, see split_rows)
        caps = np.maximum(1, 1 << np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64))
        caps = np.minimum(caps, max_capacity)

        self.buckets: List[Tuple] = []
        self.vertex_order_parts: List[np.ndarray] = []
        src32 = np.ascontiguousarray(src, dtype=np.int32)
        w32 = (
            np.ascontiguousarray(w, dtype=np.float32) if w is not None else None
        )
        for c in sorted(set(int(c) for c in np.unique(caps))):
            members = np.nonzero(caps == c)[0]
            if len(members) == 0:
                continue
            deg_m = deg[members]
            starts_m = indptr[members]
            if c == max_capacity and int(deg_m.max()) > c:
                starts_r, degs_r, rowseg = split_rows(members, deg_m, starts_m, c)
            else:
                starts_r, degs_r, rowseg = starts_m, deg_m, None
            rows = len(starts_r)
            idx = np.full((rows, c), self.sentinel, dtype=np.int32)
            # unweighted packs carry idx ONLY: padded slots point at the
            # sentinel, which reads the monoid identity — wmat/valid would
            # triple HBM footprint and transfer for nothing (s23: 2.3GB
            # -> 0.76GB measured)
            if self.has_weight:
                wmat = np.zeros((rows, c), dtype=np.float32)
                valid = np.zeros((rows, c), dtype=np.float32)
            else:
                wmat = valid = None
            fill_ell_rows(c, starts_r, degs_r, src32, w32, idx, wmat, valid)
            self.buckets.append(
                (
                    idx,
                    wmat,
                    valid,
                    rowseg.astype(np.int32) if rowseg is not None else None,
                    len(members),
                )
            )
            self.vertex_order_parts.append(members)

        vertex_order = (
            np.concatenate(self.vertex_order_parts)
            if self.vertex_order_parts
            else np.zeros(0, dtype=np.int64)
        )
        # inverse permutation: position of vertex i in the bucketed output
        pos = np.zeros(n, dtype=np.int64)
        pos[vertex_order] = np.arange(len(vertex_order), dtype=np.int64)
        self.unpermute = pos.astype(np.int32)

    def device_put(self, jnp, sharding=None):
        """Move index/weight matrices to device once (optionally sharded)."""
        put = (lambda a: a) if sharding is None else (
            lambda a: __import__("jax").device_put(a, sharding)
        )
        self.buckets = [
            (
                put(jnp.asarray(i)),
                put(jnp.asarray(w)) if w is not None else None,
                put(jnp.asarray(v)) if v is not None else None,
                put(jnp.asarray(rs)) if rs is not None else None,
                ns,
            )
            for (i, w, v, rs, ns) in self.buckets
        ]
        self.unpermute = put(jnp.asarray(self.unpermute))
        return self


# graphlint: traced -- called from every compiled superstep body
def flat_take(jnp, tab, idx):
    """Gather rows/values of `tab` by a 2-D index matrix via a FLAT 1-D
    take + reshape. Identical semantics to tab[idx], but the (rows, 1) 2-D
    gather shape compiles pathologically on TPU (measured 197s for a
    667k-row cap-1 bucket vs 0.5s flat; run throughput is the same ~140M
    gathers/s). Shared by the single-chip and sharded ELL paths."""
    flat = idx.reshape(-1)
    if tab.ndim == 1:
        return jnp.take(tab, flat).reshape(idx.shape)
    return jnp.take(tab, flat, axis=0).reshape(idx.shape + tab.shape[1:])


def _next_pow2(v: int) -> int:
    return 1 << max(0, int(v) - 1).bit_length() if v > 1 else 1


def _is_jax(xp) -> bool:
    """jnp vs plain numpy — the aggregation bodies are xp-generic so the
    CPU oracle can replay the exact pack arithmetic in numpy."""
    return "jax" in getattr(xp, "__name__", "")


# graphlint: traced -- the fp-contraction fence of product-fed reductions
def fp_fence(xp, a):
    """Add an optimizer-opaque zero to `a` — the fp-contraction fence.

    LLVM's CPU backend may contract a float multiply into a following add
    as one fused multiply-add (single rounding), silently changing bits vs
    the numpy oracle's separately-rounded mul+add; HLO-level barriers and
    bitcasts do not survive to the emitted loop, so the fence works
    arithmetically instead: any contraction of a product into THIS add
    computes round(a*b + 0) == round(a*b) — the plain multiply's bits —
    and every downstream add sees a non-multiply operand, which cannot
    contract. The zero rides through `optimization_barrier` so the HLO
    simplifier can't fold the add away before the backend sees it. The
    numpy path adds a real zero, so both sides also normalize -0.0 to
    +0.0 identically."""
    if _is_jax(xp):
        import jax

        z = jax.lax.optimization_barrier(xp.zeros((), dtype=a.dtype))
        return a + z
    return a + a.dtype.type(0.0)


# graphlint: traced -- the shared reduction tree of every compiled superstep
def tree_reduce(xp, m, op: str):
    """Reduce axis 1 of `m` (width MUST be a power of two) through a fixed
    adjacent-pair halving tree: [a,b,c,d] -> [a+b, c+d] -> [(a+b)+(c+d)].

    This tree — not the backend's reduce — is the strategies' bitwise
    contract: any aligned power-of-two-sized contiguous sub-range of the
    leaves is a complete subtree, so a row evaluated whole (ELL) and the
    same row evaluated as chunk partials folded afterwards (hybrid tail)
    produce identical bits, on any backend that preserves elementwise
    float semantics (all of them)."""
    width = m.shape[1]
    if width & (width - 1):
        raise ValueError(f"tree_reduce width {width} is not a power of two")
    while m.shape[1] > 1:
        a = m[:, 0::2]
        b = m[:, 1::2]
        if op == Combiner.SUM:
            m = a + b
        elif op == Combiner.MIN:
            m = xp.minimum(a, b)
        else:
            m = xp.maximum(a, b)
    return m[:, 0]


def _segment_combine(xp, op: str, values, seg, num_segments: int):
    """Per-slot monoid fold of row partials (rows-sized, not edges-sized).
    jax path: XLA segment ops; numpy path: unbuffered ufunc.at — each
    executor's two strategies share one implementation, so hybrid-vs-ELL
    stays bitwise-identical within either executor."""
    if _is_jax(xp):
        import jax

        seg_fn = {
            Combiner.SUM: jax.ops.segment_sum,
            Combiner.MIN: jax.ops.segment_min,
            Combiner.MAX: jax.ops.segment_max,
        }[op]
        return seg_fn(values, seg, num_segments=num_segments)
    return _segment_combine_host(xp, op, values, seg, num_segments)


# graphlint: host -- numpy-only branch, unreachable from traced code
def _segment_combine_host(xp, op: str, values, seg, num_segments: int):
    out = xp.full(
        (num_segments,) + values.shape[1:], Combiner.IDENTITY[op],
        dtype=values.dtype,
    )
    ufunc = {
        Combiner.SUM: xp.add, Combiner.MIN: xp.minimum,
        Combiner.MAX: xp.maximum,
    }[op]
    ufunc.at(out, seg, values)
    return out


# graphlint: traced -- the ELL aggregation body of every compiled superstep
def ell_aggregate(
    jnp,
    pack: ELLPack,
    msgs,
    op: str,
    edge_transform: str = EdgeTransform.NONE,
    edge_transform_cols=None,
):
    """Aggregate per-vertex messages over an ELLPack.

    msgs: (n,) or (n, k) per-source message array. Returns (n,) / (n, k)
    aggregated-by-destination, monoid identity where a vertex has no edges.
    `edge_transform_cols`: per-column transforms for k-column messages
    (see vertex_program.apply_edge_transform).
    """
    identity = Combiner.IDENTITY[op]
    if not pack.has_weight:
        # mirror the segment path: transforms only apply when weights exist
        edge_transform = EdgeTransform.NONE
        edge_transform_cols = None
    # sentinel slot so padded indices read the identity
    pad_shape = (1,) + tuple(msgs.shape[1:])
    msgs_ext = jnp.concatenate(
        [msgs, jnp.full(pad_shape, identity, dtype=msgs.dtype)], axis=0
    )
    parts = []
    for idx, w, valid, rowseg, num_slots in pack.buckets:
        m = flat_take(jnp, msgs_ext, idx)
        if w is not None:
            # weighted pack: apply the transform, then force padded slots
            # back to the identity (a transform can disturb it, e.g.
            # identity*0 = nan for MIN's +inf)
            valid_ = valid[:, :, None] if m.ndim == 3 else valid
            if edge_transform_cols is not None:
                m = apply_edge_transform(
                    jnp, m, w, edge_transform, edge_transform_cols
                )
            else:
                w_ = w[:, :, None] if m.ndim == 3 else w
                if edge_transform == EdgeTransform.MUL_WEIGHT:
                    m = m * w_
                elif edge_transform == EdgeTransform.ADD_WEIGHT:
                    m = m + w_
            m = jnp.where(valid_ > 0, m, identity)
            # fence the transformed leaves so no backend contracts the
            # weight product into the reduction tree (and every layout
            # normalizes -0.0 the same way)
            m = fp_fence(jnp, m)
        # unweighted pack: padded slots index the sentinel, which already
        # reads the identity — no mask needed
        r = tree_reduce(jnp, m, op)
        if rowseg is not None:
            # fold supernode row partials into one slot per destination —
            # a rows-sized reduction, negligible next to the edge gather
            r = _segment_combine(jnp, op, r, rowseg, num_slots)
        parts.append(r)
    if not parts:
        out_shape = msgs.shape
        return jnp.full(out_shape, identity, dtype=msgs.dtype)
    stacked = jnp.concatenate(parts, axis=0)
    return stacked[pack.unpermute]


# --------------------------------------------------------------------------
# Degree-bucketed hybrid: exact-width ELL torso + chunked CSR tail
# --------------------------------------------------------------------------

class HybridPack:
    """Hybrid layout of an edge list grouped by destination degree.

    Torso (in-degree 1..hub_cutoff): one bucket per EXACT degree d — a
    (rows, d) source-index matrix with no padded slots at all; the
    reduction pads to next-pow2(d) with the monoid identity *in-kernel*
    (registers/VMEM, never gathered), reproducing the pure-ELL bucket's
    leaves exactly. Zero-degree vertices contribute an identity constant
    and zero slots.

    Tail (hub vertices, in-degree > hub_cutoff): the hubs' destination-
    sorted CSR edge ranges are cut into contiguous `tail_chunk`-wide
    chunks (the last chunk of a row sentinel-padded — static tail capacity
    tiers); chunk partials scatter into an identity-filled per-row partial
    table of width cap/tail_chunk and fold down the remaining tree levels.
    Degrees above `max_capacity` row-split first, exactly like ELLPack
    (shared `split_rows`), so the final rows-sized segment fold sees the
    same operand sequence.

    Both `tail_chunk` and every tree width are powers of two, so every
    vertex reduces through the identical `tree_reduce` tree the ELL path
    uses — hybrid and ELL results are bitwise-equal by construction.
    Slots actually gathered: m_torso exact + ceil-per-hub-row chunk
    padding, i.e. pad_ratio ~ 1 + tail_chunk/(2*mean hub degree) instead
    of ELL's 1.4-1.5x pow2 rounding.
    """

    def __init__(
        self,
        src: np.ndarray,
        dst: np.ndarray,
        weight: Optional[np.ndarray],
        num_vertices: int,
        hub_cutoff: int = 64,
        tail_chunk: int = 256,
        max_capacity: int = 1 << 14,
    ):
        n = num_vertices
        self.num_vertices = n
        self.sentinel = n
        self.has_weight = weight is not None
        self.hub_cutoff = int(hub_cutoff)
        tail_chunk = int(tail_chunk)
        if tail_chunk < 1 or tail_chunk & (tail_chunk - 1):
            raise ValueError(
                f"tail_chunk must be a power of two (got {tail_chunk})"
            )
        if self.hub_cutoff < 1:
            raise ValueError(f"hub_cutoff must be >= 1 (got {hub_cutoff})")
        # every hub's tree width is >= next_pow2(cutoff+1); the chunk must
        # divide it so chunks stay aligned subtrees
        self.tail_chunk = min(
            tail_chunk, _next_pow2(self.hub_cutoff + 1), int(max_capacity)
        )

        order = np.argsort(dst, kind="stable")
        src = np.asarray(src, dtype=np.int64)[order]
        dst = np.asarray(dst, dtype=np.int64)[order]
        w = (
            np.asarray(weight, dtype=np.float32)[order]
            if weight is not None
            else None
        )
        deg = np.bincount(dst, minlength=n).astype(np.int64)
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=indptr[1:])
        src32 = np.ascontiguousarray(src, dtype=np.int32)
        w32 = (
            np.ascontiguousarray(w, dtype=np.float32) if w is not None else None
        )

        vertex_order_parts: List[np.ndarray] = []
        #: torso buckets: array dicts ({"idx", "w"?}) + static (width, tree cap)
        self.torso: List[dict] = []
        self.torso_meta: List[Tuple[int, int]] = []
        torso_degrees = np.unique(deg[(deg >= 1) & (deg <= self.hub_cutoff)])
        for d in (int(x) for x in torso_degrees):
            members = np.nonzero(deg == d)[0]
            pos = indptr[members][:, None] + np.arange(d, dtype=np.int64)
            entry = {"idx": src32[pos]}
            if self.has_weight:
                entry["w"] = w32[pos]
            self.torso.append(entry)
            self.torso_meta.append((d, _next_pow2(d)))
            vertex_order_parts.append(members)

        zero_members = np.nonzero(deg == 0)[0]
        self.num_zero = len(zero_members)
        if self.num_zero:
            vertex_order_parts.append(zero_members)

        #: tail buckets: array dicts ({"idx", "slot", "w"?, "valid"?,
        #: "rowseg"?}) + static (tree cap, partials per row, rows, slots)
        self.tail: List[dict] = []
        self.tail_meta: List[Tuple[int, int, int, int]] = []
        T = self.tail_chunk
        hub = deg > self.hub_cutoff
        if hub.any():
            caps = np.minimum(
                1 << np.ceil(
                    np.log2(np.maximum(deg, 1))
                ).astype(np.int64),
                int(max_capacity),
            )
            for c in sorted(int(x) for x in np.unique(caps[hub])):
                members = np.nonzero(hub & (caps == c))[0]
                deg_m = deg[members]
                starts_m = indptr[members]
                if c == int(max_capacity) and int(deg_m.max()) > c:
                    starts_r, degs_r, rowseg = split_rows(
                        members, deg_m, starts_m, c
                    )
                else:
                    starts_r, degs_r, rowseg = starts_m, deg_m, None
                rows = len(starts_r)
                ppr = c // T  # partial-table width per row
                nch = -(-degs_r // T)  # real chunks per row (degs_r >= 1)
                total = int(nch.sum())
                row_of = np.repeat(np.arange(rows, dtype=np.int64), nch)
                posr = (
                    np.arange(total, dtype=np.int64)
                    - np.repeat(np.cumsum(nch) - nch, nch)
                )
                ch_start = starts_r[row_of] + posr * T
                ch_deg = np.minimum(T, degs_r[row_of] - posr * T)
                idx = np.full((total, T), self.sentinel, dtype=np.int32)
                if self.has_weight:
                    wmat = np.zeros((total, T), dtype=np.float32)
                    valid = np.zeros((total, T), dtype=np.float32)
                else:
                    wmat = valid = None
                fill_ell_rows(T, ch_start, ch_deg, src32, w32, idx, wmat, valid)
                entry = {
                    "idx": idx,
                    "slot": (row_of * ppr + posr).astype(np.int32),
                }
                if wmat is not None:
                    entry["w"] = wmat
                    entry["valid"] = valid
                if rowseg is not None:
                    entry["rowseg"] = rowseg.astype(np.int32)
                self.tail.append(entry)
                self.tail_meta.append((c, ppr, rows, len(members)))
                vertex_order_parts.append(members)

        vertex_order = (
            np.concatenate(vertex_order_parts)
            if vertex_order_parts
            else np.zeros(0, dtype=np.int64)
        )
        pos = np.zeros(n, dtype=np.int64)
        pos[vertex_order] = np.arange(len(vertex_order), dtype=np.int64)
        self.unpermute = pos.astype(np.int32)
        #: gathered slots (the bandwidth-proportional number the pad ratio
        #: prices); partial tables are rows-sized and excluded
        self.slots = sum(int(b["idx"].size) for b in self.torso) + sum(
            int(b["idx"].size) for b in self.tail
        )
        self.pad_ratio = self.slots / max(1, len(src))

    def device_put(self, jnp, sharding=None):
        """Move index/weight/slot matrices to device once."""
        put = (lambda a: a) if sharding is None else (
            lambda a: __import__("jax").device_put(a, sharding)
        )
        self.torso = [
            {k: put(jnp.asarray(v)) for k, v in b.items()} for b in self.torso
        ]
        self.tail = [
            {k: put(jnp.asarray(v)) for k, v in b.items()} for b in self.tail
        ]
        self.unpermute = put(jnp.asarray(self.unpermute))
        return self


class HybridPackView:
    """HybridPack-shaped facade over traced bucket arrays (duck-typed for
    hybrid_aggregate), carrying the compiled variant's static metadata."""

    __slots__ = (
        "torso", "torso_meta", "tail", "tail_meta", "num_zero",
        "unpermute", "has_weight",
    )

    def __init__(self, args, pack: HybridPack):
        if len(args["torso"]) != len(pack.torso_meta) or len(
            args["tail"]
        ) != len(pack.tail_meta):
            raise ValueError(
                f"graph-args hybrid bucket counts "
                f"({len(args['torso'])}/{len(args['tail'])}) != compiled "
                f"metadata ({len(pack.torso_meta)}/{len(pack.tail_meta)}) "
                f"(pack drift)"
            )
        self.torso = args["torso"]
        self.tail = args["tail"]
        self.unpermute = args["unpermute"]
        self.torso_meta = pack.torso_meta
        self.tail_meta = pack.tail_meta
        self.num_zero = pack.num_zero
        self.has_weight = pack.has_weight


# graphlint: traced -- the hybrid aggregation body of compiled supersteps
def hybrid_aggregate(
    xp,
    pack,
    msgs,
    op: str,
    edge_transform: str = EdgeTransform.NONE,
    edge_transform_cols=None,
):
    """Aggregate per-vertex messages over a HybridPack (or view).

    Same contract as ell_aggregate — msgs (n,) or (n, k), returns the
    per-destination monoid fold — and bitwise-identical results to it
    (both reduce through tree_reduce's fixed adjacent-pair tree)."""
    identity = Combiner.IDENTITY[op]
    if not pack.has_weight:
        edge_transform = EdgeTransform.NONE
        edge_transform_cols = None
    pad_shape = (1,) + tuple(msgs.shape[1:])
    msgs_ext = xp.concatenate(
        [msgs, xp.full(pad_shape, identity, dtype=msgs.dtype)], axis=0
    )

    def transform(m, w, valid):
        # mirrors the ELL weighted path slot-for-slot: transform first,
        # then force padded slots back to the identity (a transform can
        # disturb it, e.g. identity*0 = nan for MIN's +inf)
        if w is None:
            return m
        if edge_transform_cols is not None:
            m = apply_edge_transform(
                xp, m, w, edge_transform, edge_transform_cols
            )
        else:
            w_ = w[:, :, None] if m.ndim == 3 else w
            if edge_transform == EdgeTransform.MUL_WEIGHT:
                m = m * w_
            elif edge_transform == EdgeTransform.ADD_WEIGHT:
                m = m + w_
        if valid is not None:
            valid_ = valid[:, :, None] if m.ndim == 3 else valid
            m = xp.where(valid_ > 0, m, identity)
        # same fence as the ELL weighted branch: the torso's unmasked
        # weight product would otherwise contract into the tree
        return fp_fence(xp, m)

    parts = []
    for entry, (d, cap) in zip(pack.torso, pack.torso_meta):
        m = flat_take(xp, msgs_ext, entry["idx"])  # (rows, d[, k])
        m = transform(m, entry.get("w"), None)
        if cap > d:
            # in-kernel identity pad up to the pow2 tree width: same
            # leaves as the ELL bucket's sentinel slots, never gathered
            fill = xp.full(
                (m.shape[0], cap - d) + tuple(m.shape[2:]), identity,
                dtype=m.dtype,
            )
            m = xp.concatenate([m, fill], axis=1)
        parts.append(tree_reduce(xp, m, op))

    if pack.num_zero:
        parts.append(
            xp.full(
                (pack.num_zero,) + tuple(msgs.shape[1:]), identity,
                dtype=msgs.dtype,
            )
        )

    for entry, (cap, ppr, rows, num_slots) in zip(pack.tail, pack.tail_meta):
        m = flat_take(xp, msgs_ext, entry["idx"])  # (chunks, T[, k])
        m = transform(m, entry.get("w"), entry.get("valid"))
        part = tree_reduce(xp, m, op)  # (chunks[, k]) — aligned subtrees
        tab_shape = (rows * ppr,) + tuple(part.shape[1:])
        if _is_jax(xp):
            table = xp.full(tab_shape, identity, dtype=part.dtype)
            table = table.at[entry["slot"]].set(part)
        else:
            table = xp.full(tab_shape, identity, dtype=part.dtype)
            table[entry["slot"]] = part
        # remaining upper tree levels: fold the per-row partial vector
        table = table.reshape((rows, ppr) + tuple(part.shape[1:]))
        r = tree_reduce(xp, table, op)
        rowseg = entry.get("rowseg")
        if rowseg is not None:
            r = _segment_combine(xp, op, r, rowseg, num_slots)
        parts.append(r)

    if not parts:
        return xp.full(msgs.shape, identity, dtype=msgs.dtype)
    stacked = xp.concatenate(parts, axis=0)
    return stacked[pack.unpermute]


# --------------------------------------------------------------------------
# Pallas sorted-segment-sum
# --------------------------------------------------------------------------

class _SegSumPlan:
    """Static host-side plan: tile-aligned edge blocks for the kernel.

    Edges (sorted by destination segment) are re-laid-out so each output
    tile's edge range occupies whole blocks; a block therefore writes into
    exactly one output tile, enabling the revisit-accumulate output pattern.
    """

    def __init__(
        self,
        seg: np.ndarray,
        num_segments: int,
        block: int = 1024,
        tile: int = 1024,
    ):
        self.block = block
        self.tile = tile
        self.num_segments = num_segments
        self.padded_segments = -(-max(num_segments, 1) // tile) * tile
        num_tiles = self.padded_segments // tile

        seg = np.asarray(seg, dtype=np.int64)
        m = len(seg)
        # edges per output tile (seg already sorted ascending)
        tile_of = seg // tile
        counts = np.bincount(tile_of, minlength=num_tiles)
        blocks_per_tile = np.maximum(1, -(-counts // block))
        total_blocks = int(blocks_per_tile.sum())
        padded_m = total_blocks * block

        gather_idx = np.zeros(padded_m, dtype=np.int32)
        pad_mask = np.zeros(padded_m, dtype=np.float32)
        seg_local = np.zeros(padded_m, dtype=np.int32)
        out_tile = np.zeros(total_blocks, dtype=np.int32)
        is_first = np.zeros(total_blocks, dtype=np.int32)

        edge_starts = np.zeros(num_tiles + 1, dtype=np.int64)
        np.cumsum(counts, out=edge_starts[1:])
        b = 0
        w = 0
        for t in range(num_tiles):
            lo, hi = edge_starts[t], edge_starts[t + 1]
            k = hi - lo
            gather_idx[w : w + k] = np.arange(lo, hi, dtype=np.int32)
            pad_mask[w : w + k] = 1.0
            seg_local[w : w + k] = (seg[lo:hi] - t * tile).astype(np.int32)
            nb = int(blocks_per_tile[t])
            out_tile[b : b + nb] = t
            is_first[b] = 1
            b += nb
            w += nb * block
        self.gather_idx = gather_idx
        self.pad_mask = pad_mask
        self.seg_local = seg_local
        self.out_tile = out_tile
        self.is_first = is_first
        self.num_blocks = total_blocks


def make_segsum_plan(
    seg: np.ndarray, num_segments: int, block: int = 1024, tile: int = 1024
) -> _SegSumPlan:
    return _SegSumPlan(seg, num_segments, block=block, tile=tile)


def pallas_sorted_segment_sum(
    data,
    plan: _SegSumPlan,
    interpret: bool = False,
):
    """Segment-sum of `data` (per-edge values, original edge order) using a
    Pallas TPU kernel over the precomputed tile-aligned plan.

    Returns (num_segments,) float32 sums.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T = plan.block, plan.tile

    # align + pad on device (monotone gather, cheap)
    gidx = jnp.asarray(plan.gather_idx)
    mask = jnp.asarray(plan.pad_mask)
    segl = jnp.asarray(plan.seg_local)
    data_p = data[gidx] * mask

    def kernel(out_tile_ref, is_first_ref, data_ref, seg_ref, out_ref):
        b = pl.program_id(0)

        @pl.when(is_first_ref[b] == 1)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        seg_block = seg_ref[:]                      # (B,)
        d = data_ref[:]                             # (B,)
        cols = jax.lax.broadcasted_iota(jnp.int32, (B, T), 1)
        onehot = (seg_block[:, None] == cols).astype(jnp.float32)
        partial = jnp.dot(
            d.reshape(1, B), onehot, preferred_element_type=jnp.float32
        ).reshape(T)
        out_ref[:] = out_ref[:] + partial

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(plan.num_blocks,),
        in_specs=[
            pl.BlockSpec((B,), lambda b, ot, fi: (b,)),
            pl.BlockSpec((B,), lambda b, ot, fi: (b,)),
        ],
        out_specs=pl.BlockSpec((T,), lambda b, ot, fi: (ot[b],)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((plan.padded_segments,), jnp.float32),
        interpret=interpret,
    )(
        jnp.asarray(plan.out_tile),
        jnp.asarray(plan.is_first),
        data_p.astype(jnp.float32),
        segl,
    )
    return out[: plan.num_segments]
