"""Frontier-compacted SSSP/BFS supersteps (push-style, capped expansion).

Reference behavior modeled: FulgoraGraphComputer special-cases the
ShortestPath programs rather than running them through the generic BSP loop
(reference: janusgraph-core .../olap/computer/FulgoraGraphComputer.java:249-253).
The TPU-native form of that special case is *frontier compaction*: a dense
superstep gathers every edge every superstep — at the measured v5e gather
wall (~140M gathered elem/s, docs/tpu_notes.md) that is ~1.9 s/superstep at
scale 23 even when the BFS frontier is a handful of vertices. Here each hop:

  1. compacts the active frontier to a capped index buffer
     (``jnp.nonzero(size=F_cap)`` — static shape, XLA-friendly),
  2. expands it to a capped edge buffer via scatter+cumsum "pointer
     spreading" (NO searchsorted: binary search is itself a gather chain
     and would re-hit the gather wall),
  3. gathers only the frontier's out-neighbors (E_frontier elements, not E),
  4. scatter-mins the relaxed distances into the state.

Tiers: one executable per (F_cap, E_cap) pair, caps growing in powers of 4
up to (n, m) — the top tier IS the dense fallback, so a saturated frontier
costs one full-edge pass and nothing is ever dropped. Per-step results are
bit-identical to the dense BSP path: relaxing a non-frontier edge is a
no-op (its source's distance has not changed since it was last relaxed), so
skipping it cannot change any superstep's output, weighted or not.

Int32 throughout (the telescoping cumsum trick needs diff headroom, hence
the ``m < 2**30`` eligibility guard — beyond that the executor keeps the
dense path).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# the reached-ness tests below ("dist >= INF") are parity-equivalent to the
# dense program only because both use the IDENTICAL constant
from janusgraph_tpu.olap.programs.shortest_path import INF


def _tier(need: int, lo: int, hi: int, growth: int = 4) -> int:
    """Smallest `growth`-power multiple of `lo`, >= need, clamped to hi
    (callers guarantee hi >= need). Growth trades executable count for
    capacity fit (computer.frontier-tier-growth)."""
    if growth < 2:
        raise ValueError(
            f"frontier tier growth must be >= 2 (got {growth})"
        )
    c = lo
    while c < need:
        c *= growth
    return min(c, hi)


# graphlint: traced -- shared by the single-chip and sharded frontier steps
def capped_expand(jnp, idx, indptr, dst, E_cap, sentinel):
    """Capped frontier expansion: frontier rows -> (owner slot, edge pos,
    neighbor, valid) buffers of static length E_cap. Shared by the
    single-chip and sharded engines (the sharded CSC is over message-table
    slots with a local-destination sentinel; here over vertices).

    own/pos come from scatter+cumsum over the *frontier-sized* start
    offsets (telescoping piecewise-constant encoding) — per-slot cost is
    two vector cumsums plus ONE m-table gather (dst), instead of a
    log(F)-deep searchsorted gather chain. Requires total edges < 2^31
    (int32 telescoping headroom; callers guard at MAX_EDGES = 2^30).
    """
    F_cap = idx.shape[0]
    starts = indptr[idx]
    degs = indptr[idx + 1] - starts
    cum = jnp.cumsum(degs)
    total = cum[-1]
    cum_ex = cum - degs
    # ownership: +1 at each row's first slot (row 0 starts at owner 0);
    # deg-0 rows collapse onto the next row's start and the scatter-adds
    # accumulate, so cumsum lands on the LAST row covering a slot
    inc = jnp.ones((F_cap,), jnp.int32).at[0].set(0)
    own = jnp.cumsum(
        jnp.zeros((E_cap,), jnp.int32).at[cum_ex].add(inc, mode="drop")
    )
    # edge position: pos[s] = s + (starts - cum_ex)[own[s]], encoded the
    # same way (scatter the base DIFFS, cumsum telescopes them)
    base = starts - cum_ex
    dbase = jnp.concatenate([base[:1], jnp.diff(base)])
    pos = jnp.arange(E_cap, dtype=jnp.int32) + jnp.cumsum(
        jnp.zeros((E_cap,), jnp.int32).at[cum_ex].add(dbase, mode="drop")
    )
    valid = jnp.arange(E_cap, dtype=jnp.int32) < total
    pos = jnp.clip(pos, 0, dst.shape[0] - 1)
    nbr = jnp.where(valid, dst[pos], jnp.int32(sentinel))
    return own, pos, nbr, valid


class FrontierEngine:
    """Per-executor engine: owns the device-resident CSR pointer arrays and
    the tier-compiled step executables for ShortestPath-family programs."""

    F_MIN = 1 << 10
    E_MIN = 1 << 13
    GROWTH = 4
    #: int32 telescoping headroom (see module docstring)
    MAX_EDGES = 1 << 30

    def __init__(self, executor):
        self.ex = executor
        self.jax = executor.jax
        self.jnp = executor.jnp
        # computer.frontier-f-min / frontier-e-min overrides
        if getattr(executor, "_frontier_f_min", None):
            self.F_MIN = executor._frontier_f_min
        if getattr(executor, "_frontier_e_min", None):
            self.E_MIN = executor._frontier_e_min
        if getattr(executor, "_frontier_tier_growth", None):
            self.GROWTH = executor._frontier_tier_growth
        # autotuned tier ladders (olap/autotune.decide_tiers): explicit
        # pow2 schedules sized from the degree histogram replace the fixed
        # growth-factor ladder when the executor carries a decision
        self.f_schedule = self.e_schedule = None
        # decisions are keyed (undirected, feature_dim); frontier programs
        # are scalar-message in-CSR, so the (False, 0) decision applies
        decision = getattr(executor, "_autotune_decisions", {}).get(
            (False, 0)
        )
        if decision is not None and getattr(
            executor, "_autotune_enabled", False
        ):
            self.f_schedule = decision.f_schedule
            self.e_schedule = decision.e_schedule
        csr = executor.csr
        jnp = self.jnp
        self.n = csr.num_vertices
        self.m = csr.num_edges
        if self.m >= self.MAX_EDGES:
            raise ValueError("frontier engine requires < 2^30 edges")
        self._fargs_cache = {}
        self._plans = {}

    def _orientation_args(self, prefix: str):
        """Device arrays for one orientation, built on first use — a
        directed run never transfers the in-side O(E) arrays. dst/src
        reuse the executor's lazy device copies (no 2nd transfer); the
        pointer/degree vectors are O(n). Weights are attached separately
        (`_fargs`) so unweighted runs never force the O(E) weight
        transfer."""
        csr, g, jnp = self.ex.csr, self.ex.g, self.jnp
        args = self._fargs_cache.get(prefix)
        if args is None:
            if prefix == "out":
                indptr, edges = csr.out_indptr, g.out_dst
            else:
                indptr, edges = csr.in_indptr, g.in_src
            args = {
                # indptr padded to n+2: a sentinel row (idx n) reads deg 0
                f"{prefix}_ip": jnp.asarray(
                    np.concatenate([indptr, indptr[-1:]]).astype(np.int32)
                ),
                "out_dst" if prefix == "out" else "in_src": edges,
                f"{prefix}_deg": jnp.asarray(
                    np.diff(indptr).astype(np.int32)
                ),
            }
            self._fargs_cache[prefix] = args
        return args

    def _fargs(self, undirected: bool, weighted: bool):
        g = self.ex.g
        args = dict(self._orientation_args("out"))
        if undirected:
            args.update(self._orientation_args("in"))
        if weighted:
            if g.out_edge_weight is not None:
                args["out_w"] = g.out_edge_weight
            if undirected and g.in_edge_weight is not None:
                args["in_w"] = g.in_edge_weight
        return args

    # ------------------------------------------------------------------ plan
    def _plan_fn(self, undirected: bool):
        """(mask, fargs) -> (frontier count, out-edge total, in-edge total):
        O(n) vector work, one fetch of three scalars per hop."""
        plan = self._plans.get(undirected)
        if plan is not None:
            return plan
        jnp = self.jnp

        def plan_body(mask, fargs):
            zero = jnp.zeros((), jnp.int32)
            count = jnp.sum(mask.astype(jnp.int32))
            tot_out = jnp.sum(jnp.where(mask, fargs["out_deg"], zero))
            tot_in = (
                jnp.sum(jnp.where(mask, fargs["in_deg"], zero))
                if undirected
                else zero
            )
            return count, tot_out, tot_in

        plan = self.jax.jit(plan_body)
        self._plans[undirected] = plan
        return plan

    # ------------------------------------------------------------------ step
    def _expand(self, idx, indptr, dst, E_cap):
        """See capped_expand (module level; shared with the sharded
        engine): sentinel = n, the dead scatter slot."""
        return capped_expand(self.jnp, idx, indptr, dst, E_cap, self.n)

    def _step_fn(self, F_cap, E_cap, weighted, track_paths, undirected):
        key = ("frontier-step", F_cap, E_cap, weighted, track_paths, undirected)
        cache = self.ex._compiled
        if key in cache:
            return cache[key]
        jnp = self.jnp
        n = self.n

        def one_orientation(tmp, dist, idx, indptr, dst, w):
            own, pos, nbr, valid = self._expand(idx, indptr, dst, E_cap)
            if weighted:
                # message = sender distance (+ edge weight when present);
                # invalid slots target the sentinel row, but mask the value
                # anyway so a clamped gather can never leak a finite number
                dist_f = dist[jnp.clip(idx, 0, n - 1)]
                msg = dist_f[own]
                if w is not None:
                    msg = msg + w[pos]
            elif track_paths:
                # message = sender's (global) vertex index; MIN-combining
                # yields the smallest-index frontier predecessor — the same
                # encoding the dense program uses (programs/shortest_path.py)
                msg = idx.astype(jnp.float32)[own]
            else:
                # unweighted: any finite marker means "reached this hop"
                msg = jnp.zeros((E_cap,), jnp.float32)
            msg = jnp.where(valid, msg, INF)
            return tmp.at[nbr].min(msg)

        def step(dist, pred, mask, t, fargs):
            idx = jnp.nonzero(mask, size=F_cap, fill_value=n)[0]
            idx = idx.astype(jnp.int32)
            tmp = jnp.full((n + 1,), INF, jnp.float32)
            tmp = one_orientation(
                tmp, dist, idx, fargs["out_ip"], fargs["out_dst"],
                fargs.get("out_w") if weighted else None,
            )
            if undirected:
                tmp = one_orientation(
                    tmp, dist, idx, fargs["in_ip"], fargs["in_src"],
                    fargs.get("in_w") if weighted else None,
                )
            tmp = tmp[:n]
            if weighted:
                new = jnp.minimum(dist, tmp)
                changed = new < dist
                return new, pred, changed, jnp.sum(changed.astype(jnp.int32))
            newly = (dist >= INF) & (tmp < INF)
            new = jnp.where(newly, t + 1.0, dist)
            if track_paths:
                pred = jnp.where(newly, tmp, pred)
            return new, pred, newly, jnp.sum(newly.astype(jnp.int32))

        fn = self.jax.jit(step)
        cache[key] = fn
        return fn

    # ------------------------------------------------------------------- run
    def _hop_loop(
        self, value, pred, mask, weighted, track, und, fargs, max_iterations
    ):
        """The shared host-driven loop: plan (3 scalars) -> pick tier ->
        one compiled step. Two device round trips per hop; per-step output
        is identical to the dense BSP path's."""
        jax, jnp = self.jax, self.jnp
        plan = self._plan_fn(und)
        if self.m == 0:
            mask = jnp.zeros_like(mask)
        trace = []
        for t in range(max_iterations):
            count, tot_out, tot_in = (
                int(x) for x in jax.device_get(plan(mask, fargs))
            )
            if count == 0:
                break
            if self.f_schedule and self.e_schedule:
                from janusgraph_tpu.olap.autotune import pick_tier

                f_cap = pick_tier(count, self.f_schedule, self.n)
                e_cap = pick_tier(
                    max(tot_out, tot_in, 1), self.e_schedule, self.m
                )
            else:
                f_cap = _tier(count, self.F_MIN, self.n, self.GROWTH)
                e_cap = _tier(
                    max(tot_out, tot_in, 1), self.E_MIN, self.m, self.GROWTH
                )
            trace.append(
                {"hop": t, "frontier": count,
                 "edges": max(tot_out, tot_in), "F_cap": f_cap,
                 "E_cap": e_cap,
                 "tier_source": (
                     "autotune" if self.e_schedule else "static"
                 )}
            )
            fn = self._step_fn(f_cap, e_cap, weighted, track, und)
            value, pred, mask, _ = fn(
                value, pred, mask, jnp.asarray(t, jnp.float32), fargs
            )
        # observability: which tiers each hop actually priced at — the
        # per-hop analogue of .profile() (read via executor.last_run_info)
        self.last_trace = trace
        return value, pred

    def run(self, program) -> Dict[str, np.ndarray]:
        """SSSP/BFS through the shared hop loop."""
        jnp = self.jnp
        n = self.n
        weighted = program.weighted
        track = program.track_paths
        und = program.undirected
        idx0 = np.arange(n, dtype=np.int64)
        dist = jnp.asarray(
            np.where(idx0 == program.seed_index, 0.0, INF), jnp.float32
        )
        pred = None
        if track:
            pred = jnp.asarray(
                np.where(
                    idx0 == program.seed_index,
                    float(program.seed_index), -1.0,
                ),
                jnp.float32,
            )
        mask = jnp.asarray(idx0 == program.seed_index)
        dist, pred = self._hop_loop(
            dist, pred, mask, weighted, track, und,
            self._fargs(und, weighted), program.max_iterations,
        )
        out = {"distance": np.asarray(dist)}
        if track:
            out["predecessor"] = np.asarray(pred)
        return out

    def run_cc(self, program) -> Dict[str, np.ndarray]:
        """Frontier-compacted connected components: min-LABEL propagation
        with a changed-vertex frontier. Reuses the weighted-relaxation step
        (message = sender's value, scatter-min, changed mask) — labels
        propagate exactly like distances with zero edge weight. Late
        supersteps touch a shrinking frontier, so fixpoint convergence
        costs far less than |E| per superstep (the dense path's price).
        Per-step parity with the dense BSP path: an unchanged vertex's
        label was already absorbed by its neighbors when it last changed.
        Labels ride float32 (exact below 2^24 — eligibility-guarded)."""
        jnp = self.jnp
        labels = jnp.asarray(np.arange(self.n, dtype=np.float32))
        mask = jnp.ones((self.n,), bool)
        # both orientations, NO weight arrays: the step fn's value-message
        # branch adds w[pos] whenever weights are present in fargs, and a
        # label must never absorb an edge weight
        labels, _ = self._hop_loop(
            labels, None, mask, True, False, True,
            self._fargs(True, False), program.max_iterations,
        )
        return {"component": np.asarray(labels)}
