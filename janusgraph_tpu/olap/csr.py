"""Bulk load: storage rows -> CSR adjacency blocks (the OLAP substrate).

This replaces the reference's rescan-per-superstep architecture
(reference: graphdb/olap/computer/FulgoraGraphComputer.java:210-230 re-runs a
full StandardScanner edge scan every BSP iteration, with messages pulled
through reversed slice queries — VertexProgramScanJob.java:114-135): we scan
ONCE, decode the adjacency into dense numpy CSR/CSC arrays, and run every
superstep over in-memory (then in-HBM) arrays. Ghost vertices (rows without
the vertex-existence cell) are skipped exactly like the reference's
VertexJobConverter.java:126 ghost check; partitioned (vertex-cut) vertices
are canonicalized during load, which subsumes the reference's
PartitionedVertexProgramExecutor merge pass.

Decoding is vectorized: fixed-width edge columns (the common case) decode via
one reshape + strided views (EdgeSerializer.bulk_decode_edges); only
sort-key-bearing columns fall back to per-entry parsing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from janusgraph_tpu.core.codecs import EDGE_COL_FIXED, Direction
from janusgraph_tpu.storage.kcvs import SliceQuery


@dataclass
class CSRGraph:
    """Immutable columnar snapshot of the graph for OLAP.

    Vertices are densely indexed [0, n); `vertex_ids[i]` maps back to the
    64-bit graph id. Both edge orientations are kept:
      out CSR: out_indptr/out_dst  — messages pushed along out-edges
      in  CSR: in_indptr/in_src    — pull-based aggregation (the hot one)
    """

    vertex_ids: np.ndarray          # (n,) int64, sorted ascending
    out_indptr: np.ndarray          # (n+1,) int64
    out_dst: np.ndarray             # (m,) int32 vertex indices
    in_indptr: np.ndarray           # (n+1,) int64
    in_src: np.ndarray              # (m,) int32 vertex indices
    out_degree: np.ndarray          # (n,) int32
    in_edge_weight: Optional[np.ndarray] = None   # (m,) float32, aligned to in_src
    out_edge_weight: Optional[np.ndarray] = None  # (m,) float32, aligned to out_dst
    properties: Dict[str, np.ndarray] = field(default_factory=dict)
    labels: Optional[np.ndarray] = None  # (n,) int64 vertex-label schema ids
    # per-edge type (edge-label schema id) arrays — the substrate for typed
    # EdgeChannel views (reference: per-scope slice queries compiled at
    # VertexProgramScanJob.java:114-135 restrict each message round to one
    # edge label; here the restriction is an array mask over these)
    in_edge_type: Optional[np.ndarray] = None     # (m,) int32, aligned to in_src
    out_edge_type: Optional[np.ndarray] = None    # (m,) int32, aligned to out_dst

    @property
    def num_vertices(self) -> int:
        return len(self.vertex_ids)

    # uniform interface with sharded views: a single-chip CSRGraph is one
    # shard holding everything, with no padding
    @property
    def local_num_vertices(self) -> int:
        return len(self.vertex_ids)

    @property
    def global_offset(self) -> int:
        return 0

    @property
    def active(self):
        """1.0 for real vertices, 0.0 for SPMD padding slots. Programs whose
        global metrics would be polluted by padding mask with this."""
        return np.ones(len(self.vertex_ids))

    @property
    def num_edges(self) -> int:
        return len(self.out_dst)

    @property
    def in_degree(self) -> np.ndarray:
        """(n,) int32 in-degrees (derived from in_indptr, cached). The
        dense-feature tier's mean-aggregation normalizer; the device view
        exposes the same field as float32."""
        cached = getattr(self, "_in_degree_cache", None)
        if cached is None:
            cached = np.diff(self.in_indptr).astype(np.int32)
            object.__setattr__(self, "_in_degree_cache", cached)
        return cached

    def index_of(self, vid: int) -> int:
        i = int(np.searchsorted(self.vertex_ids, vid))
        if i >= len(self.vertex_ids) or self.vertex_ids[i] != vid:
            raise KeyError(f"vertex id {vid} not in snapshot")
        return i

    def id_of(self, index: int) -> int:
        return int(self.vertex_ids[index])


def load_csr(
    graph,
    edge_labels: Optional[Sequence[str]] = None,
    property_keys: Sequence[str] = (),
    weight_key: Optional[str] = None,
    partitions: Optional[Sequence[int]] = None,
    vertex_labels: Optional[Sequence[str]] = None,
) -> CSRGraph:
    """Scan the edgestore and build a CSRGraph.

    edge_labels: restrict to these labels (None = all user edges) — the
    reference's GraphFilter.edges equivalent.
    vertex_labels: restrict to vertices with these labels — the reference's
    GraphFilter.vertices equivalent (edges incident to excluded vertices are
    dropped with them).
    property_keys: vertex property columns to materialize as arrays.
    weight_key: edge property to materialize as edge weight (float).
    partitions: restrict the scan to these storage partitions (the unit that
    maps onto mesh shards).
    """
    idm = graph.idm

    label_ids: Optional[set] = None
    if edge_labels is not None:
        label_ids = set()
        for name in edge_labels:
            el = graph.schema_cache.get_by_name(name)
            if el is not None:
                label_ids.add(el.id)

    vlabel_ids: Optional[set] = None
    if vertex_labels is not None:
        vlabel_ids = set()
        for name in vertex_labels:
            vl = graph.schema_cache.get_by_name(name)
            if vl is not None:
                vlabel_ids.add(vl.id)

    prop_key_ids: Dict[int, str] = {}
    for name in property_keys:
        pk = graph.schema_cache.get_by_name(name)
        if pk is not None:
            prop_key_ids[pk.id] = name
    weight_key_id = None
    if weight_key is not None:
        pk = graph.schema_cache.get_by_name(weight_key)
        if pk is not None:
            weight_key_id = pk.id

    raw = _scan_raw(
        graph, label_ids, vlabel_ids, prop_key_ids, weight_key_id, partitions
    )
    return build_csr_from_raw(idm, [raw])


def _scan_raw(
    graph, label_ids, vlabel_ids, prop_key_ids, weight_key_id, partitions
):
    """Partition scan -> RAW vid-space arrays with NO endpoint validation:
    the unit of DISTRIBUTED loading. Each worker scans disjoint partitions;
    an edge's destination may live in another worker's partition set, so
    validation waits for the merge (build_csr_from_raw)."""
    es = graph.edge_serializer
    idm = graph.idm
    st = graph.system_types
    btx = graph.backend.begin_transaction()
    store_tx = btx.store_tx
    store = graph.backend.edgestore

    # ONE wide slice covering every cell category (sys-prop .. user-edge):
    # the whole row arrives with the scan, so there are no per-row get_slice
    # round trips at all (VERDICT r2: the previous loop issued 3-4 per
    # vertex; reference analogue: aligned multi-query row assembly,
    # StandardScannerExecutor.java:140-174, collapsed into one range here)
    import struct as _struct

    full_q = SliceQuery(bytes([0]), bytes([4]))
    exists_tid = st.EXISTS
    label_tid = st.VERTEX_LABEL_EDGE
    label_filter = (
        np.array(sorted(label_ids), dtype=np.int64)
        if label_ids is not None
        else None
    )
    # RelationTypeIndex cells duplicate edges under the index's type id —
    # invisible to untyped edge enumeration (they'd double-count otherwise)
    relidx_ids = getattr(graph, "relation_index_ids", frozenset())
    relidx_filter = (
        np.array(sorted(relidx_ids), dtype=np.int64)
        if (relidx_ids and label_ids is None)
        else None
    )

    src_ids: List[np.ndarray] = []
    dst_ids: List[np.ndarray] = []
    weights: List[np.ndarray] = []
    etypes: List[np.ndarray] = []
    vertex_id_list: List[int] = []
    vertex_labels: List[int] = []
    raw_props: Dict[str, Dict[int, object]] = {name: {} for name in prop_key_ids.values()}

    if partitions is None:
        ranges = [idm.partition_key_range(p) for p in range(idm.num_partitions)]
    else:
        ranges = [idm.partition_key_range(p) for p in partitions]

    from janusgraph_tpu.storage.kcvs import KeyRangeQuery

    canonicalize = idm.get_canonical_vertex_id

    ordered = graph.backend.manager.features.ordered_scan

    def _scan_rows():
        if ordered:
            # per-range retry + resume (same contract as StandardScanner):
            # a TemporaryBackendError mid-stream re-issues the range from
            # just past the last yielded key, so a killed scan worker (or
            # injected chaos) costs a reconnect, not the whole load
            from janusgraph_tpu.exceptions import TemporaryBackendError

            retries = 3
            cfg = getattr(graph, "config", None)
            if cfg is not None:
                retries = cfg.get("storage.scan-retries")
            for start, end in ranges:
                cursor = start
                attempt = 0
                while True:
                    try:
                        for key, entries in store.get_keys(
                            KeyRangeQuery(cursor, end, full_q), store_tx
                        ):
                            yield key, entries
                            cursor = key + b"\x00"
                        break
                    except TemporaryBackendError:
                        attempt += 1
                        if attempt > retries:
                            raise
                        from janusgraph_tpu.observability import registry

                        registry.counter("storage.scan.retries").inc()
        else:
            # unordered backends (sharded/CQL-analogue): one full scan,
            # key-range filtering client-side (reference: token-range
            # getKeys path used by VertexJobConverter on CQL)
            for key, entries in store.get_keys(full_q, store_tx):
                if any(s <= key < e for s, e in ranges):
                    yield key, entries

    # chunked bulk decode: fixed-width edge columns accumulate across rows
    # and decode in one numpy pass per chunk
    CHUNK = 1 << 16
    pend_cols: List[bytes] = []
    pend_vids: List[int] = []
    unpack_tid = _struct.Struct(">Q").unpack_from

    def _flush_edges():
        if not pend_cols:
            return
        tids, dirs, others, _rels = es.bulk_decode_edges(pend_cols)
        owner = np.array(pend_vids, dtype=np.int64)
        pend_cols.clear()
        pend_vids.clear()
        mask = dirs == int(Direction.OUT)
        if label_filter is not None:
            mask &= np.isin(tids, label_filter)
        elif relidx_filter is not None:
            mask &= ~np.isin(tids, relidx_filter)
        if not mask.any():
            return
        src_ids.append(owner[mask])
        dst_ids.append(others[mask])
        etypes.append(tids[mask].astype(np.int32))
        if weight_key_id is not None:
            weights.append(np.ones(int(mask.sum()), dtype=np.float32))

    for key, entries in _scan_rows():
            vid = idm.get_vertex_id(key)
            if not idm.is_user_vertex_id(vid):
                continue
            vid = canonicalize(vid)

            # single pass over the row's cells, classified by category byte
            exists = False
            label_id = 0
            row_edge_cols: List[bytes] = []
            slow_entries = []
            prop_entries = []
            for col, val in entries:
                cat = col[0]
                if cat == 3:  # user edge
                    if len(col) == EDGE_COL_FIXED and not val:
                        row_edge_cols.append(col)
                    else:
                        slow_entries.append((col, val))
                elif cat == 0:  # system property
                    if unpack_tid(col, 1)[0] == exists_tid:
                        exists = True
                elif cat == 2:  # system edge (vertex label)
                    if unpack_tid(col, 1)[0] == label_tid:
                        rc = es.parse_relation((col, val), st.type_info)
                        label_id = rc.other_vertex_id
                elif cat == 1 and prop_key_ids:  # user property
                    name = prop_key_ids.get(unpack_tid(col, 1)[0])
                    if name is not None:
                        prop_entries.append((name, col, val))

            # ghost check: only rows with the existence cell are real
            # vertices (reference: VertexJobConverter.java:126) — filtered
            # rows must not pay property decode either
            if not exists:
                continue
            if vlabel_ids is not None and label_id not in vlabel_ids:
                continue
            vertex_id_list.append(vid)
            vertex_labels.append(label_id)
            for name, col, val in prop_entries:
                rc = es.parse_relation((col, val), graph_codec_schema(graph))
                raw_props[name][vid] = rc.value

            if row_edge_cols:
                pend_cols.extend(row_edge_cols)
                pend_vids.extend([vid] * len(row_edge_cols))
                if len(pend_cols) >= CHUNK:
                    _flush_edges()
            for col, val in slow_entries:
                rc = es.parse_relation((col, val), graph_codec_schema(graph))
                if rc.direction != Direction.OUT or not rc.is_edge:
                    continue
                if label_ids is not None and rc.type_id not in label_ids:
                    continue
                if label_ids is None and rc.type_id in relidx_ids:
                    continue
                src_ids.append(np.array([vid], dtype=np.int64))
                dst_ids.append(np.array([rc.other_vertex_id], dtype=np.int64))
                etypes.append(np.array([rc.type_id], dtype=np.int32))
                if weight_key_id is not None:
                    w = 1.0
                    if rc.properties and weight_key_id in rc.properties:
                        w = float(rc.properties[weight_key_id])
                    weights.append(np.array([w], dtype=np.float32))

    _flush_edges()

    return {
        "vertex_id_list": vertex_id_list,
        "vertex_labels": vertex_labels,
        "src": np.concatenate(src_ids) if src_ids else np.empty(0, np.int64),
        "dst": np.concatenate(dst_ids) if dst_ids else np.empty(0, np.int64),
        "etype": np.concatenate(etypes) if etypes else None,
        "weights": np.concatenate(weights) if weights else None,
        "raw_props": raw_props,
    }


def build_csr_from_raw(idm, raws) -> CSRGraph:
    """Merge one or more _scan_raw outputs (e.g. from N loader processes
    over disjoint partition sets) into a validated CSRGraph."""
    vid_parts, vlabel_parts = [], []
    src_parts, dst_parts, et_parts, w_parts = [], [], [], []
    raw_props: Dict[str, Dict[int, object]] = {}
    any_et = any(r["etype"] is not None for r in raws)
    any_w = any(r["weights"] is not None for r in raws)
    for r in raws:
        vid_parts.append(np.asarray(r["vertex_id_list"], dtype=np.int64))
        vlabel_parts.append(np.asarray(r["vertex_labels"], dtype=np.int64))
        src_parts.append(r["src"])
        dst_parts.append(r["dst"])
        if any_et:
            et_parts.append(
                r["etype"] if r["etype"] is not None
                else np.zeros(len(r["src"]), dtype=np.int32)
            )
        if any_w:
            w_parts.append(
                r["weights"] if r["weights"] is not None
                else np.ones(len(r["src"]), dtype=np.float32)
            )
        for name, mapping in r["raw_props"].items():
            raw_props.setdefault(name, {}).update(mapping)
    src = np.concatenate(src_parts) if src_parts else np.empty(0, np.int64)
    dst = np.concatenate(dst_parts) if dst_parts else np.empty(0, np.int64)
    et = np.concatenate(et_parts) if any_et else None
    w = np.concatenate(w_parts) if any_w else None

    # vectorized vertex/label merge: one unique pass; return_index picks a
    # representative occurrence for each id's label (the loader targets
    # multi-million-vertex merges — no per-element Python)
    vids_all = (
        np.concatenate(vid_parts) if vid_parts else np.empty(0, np.int64)
    )
    vlabels_all = (
        np.concatenate(vlabel_parts) if vlabel_parts else np.empty(0, np.int64)
    )
    vertex_ids, first_idx = np.unique(vids_all, return_index=True)
    label_arr = vlabels_all[first_idx] if len(vlabels_all) else None
    n = len(vertex_ids)
    if len(src):
        # canonicalize partitioned-vertex endpoints on the dst side too
        if idm.partition_bits > 0 and _any_partitioned(idm, dst):
            dst = canonicalize_ids(idm, dst)
        # drop edges to vertices outside the snapshot (ghost endpoints)
        src_idx = np.searchsorted(vertex_ids, src)
        dst_idx = np.searchsorted(vertex_ids, dst)
        valid = (
            (src_idx < n)
            & (dst_idx < n)
            & (vertex_ids[np.minimum(src_idx, n - 1)] == src)
            & (vertex_ids[np.minimum(dst_idx, n - 1)] == dst)
        )
        src_idx = src_idx[valid].astype(np.int32)
        dst_idx = dst_idx[valid].astype(np.int32)
        if w is not None:
            w = w[valid]
        if et is not None:
            et = et[valid]
    else:
        src_idx = np.empty(0, dtype=np.int32)
        dst_idx = np.empty(0, dtype=np.int32)
        w = None
        et = None

    # build out-CSR (sorted by src) and in-CSR (sorted by dst)
    from janusgraph_tpu import native

    out_indptr, out_dst, out_order, in_indptr, in_src, in_order = (
        native.build_csr(n, src_idx, dst_idx)
    )
    out_degree = np.diff(out_indptr).astype(np.int32)

    props: Dict[str, np.ndarray] = {}
    for name, mapping in raw_props.items():
        vals = [mapping.get(int(v)) for v in vertex_ids]
        if all(isinstance(x, (int, float)) or x is None for x in vals):
            props[name] = np.array(
                [float(x) if x is not None else np.nan for x in vals],
                dtype=np.float64,
            )
        else:
            props[name] = np.array(vals, dtype=object)

    return CSRGraph(
        vertex_ids=vertex_ids,
        out_indptr=out_indptr,
        out_dst=out_dst,
        in_indptr=in_indptr,
        in_src=in_src,
        out_degree=out_degree,
        in_edge_weight=w[in_order] if w is not None else None,
        out_edge_weight=w[out_order] if w is not None else None,
        properties=props,
        labels=label_arr,
        in_edge_type=et[in_order] if et is not None else None,
        out_edge_type=et[out_order] if et is not None else None,
    )


def _any_partitioned(idm, ids: np.ndarray) -> bool:
    # partitioned suffix is 0b010 in the low 3 bits
    return bool(np.any((ids & 0b111) == 0b010))


def canonicalize_ids(idm, ids: np.ndarray) -> np.ndarray:
    """Vectorized IDManager.get_canonical_vertex_id over an int64 array:
    partition-copies of vertex-cut vertices map to the canonical
    representative (partition = count % num_partitions); everything else
    passes through unchanged."""
    ids = np.asarray(ids, dtype=np.int64)
    # 0b010 suffix identifies partitioned user vertices (schema ids end 0b111)
    part_mask = (ids & 0b111) == 0b010
    if not np.any(part_mask):
        return ids
    pb = idm.partition_bits
    count = ids >> (3 + pb)
    canonical = (((count << pb) | (count % (1 << pb))) << 3) | 0b010
    return np.where(part_mask, canonical, ids)


def graph_codec_schema(graph):
    def lookup(type_id: int):
        info = graph.system_types.type_info(type_id)
        if info is not None:
            return info
        el = graph.schema_cache.get_by_id(type_id)
        if el is None:
            raise KeyError(type_id)
        return el.type_info()

    return lookup


def csr_from_edges(
    n: int,
    src: np.ndarray,
    dst: np.ndarray,
    weights: Optional[np.ndarray] = None,
    edge_types: Optional[np.ndarray] = None,
) -> CSRGraph:
    """Build a CSRGraph directly from an edge list with dense [0,n) ids —
    the synthetic-graph path for benchmarks (graph500 RMAT etc.).

    edge_types: optional (m,) per-edge label ids, carried into the CSR's
    in_edge_type/out_edge_type arrays for EdgeChannel views."""
    from janusgraph_tpu import native

    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    out_indptr, out_dst, out_order, in_indptr, in_src, in_order = (
        native.build_csr(n, src, dst)
    )
    et = (
        np.asarray(edge_types, dtype=np.int32)
        if edge_types is not None
        else None
    )
    return CSRGraph(
        vertex_ids=np.arange(n, dtype=np.int64),
        out_indptr=out_indptr,
        out_dst=out_dst,
        in_indptr=in_indptr,
        in_src=in_src,
        out_degree=np.diff(out_indptr).astype(np.int32),
        in_edge_weight=weights[in_order].astype(np.float32) if weights is not None else None,
        out_edge_weight=weights[out_order].astype(np.float32) if weights is not None else None,
        in_edge_type=et[in_order] if et is not None else None,
        out_edge_type=et[out_order] if et is not None else None,
    )


def load_csr_snapshot(graph, **kwargs) -> Tuple[CSRGraph, int]:
    """load_csr plus the backend mutation epoch observed BEFORE the scan —
    the handle incremental refresh resumes from."""
    epoch = graph.backend.mutation_epoch()
    csr = load_csr(graph, **kwargs)
    # refresh_csr re-derives touched rows WITHOUT filters/materialization;
    # record whether this snapshot is eligible so a filtered one fails
    # loudly instead of refreshing into an inconsistent graph
    csr._refreshable = not any(
        kwargs.get(k)
        for k in (
            "edge_labels", "vertex_labels", "property_keys",
            "weight_key", "partitions",
        )
    )
    return csr, epoch


def refresh_csr(graph, csr: CSRGraph, since_epoch: int) -> Tuple[CSRGraph, int]:
    """Incrementally fold OLTP mutations into a CSR snapshot WITHOUT
    rescanning the store (SURVEY.md §7 hard part (e): "incremental load —
    mapping OLTP mutations into CSR deltas"; the reference has no analogue —
    Fulgora rescans everything every superstep).

    Only rows the backend's mutation-epoch tracker marked since the snapshot
    are re-read; their OUT-edges are re-derived and merged with the retained
    edges of untouched rows (an edge's identity lives in its source row's
    OUT cell, and any edge mutation touches both endpoint rows, so keeping
    edges whose source row is untouched is exact). Index arrays are rebuilt
    in one native pass — O(E) compute but zero store scan. Supports
    unfiltered snapshots (no edge_labels/vertex_labels/property
    materialization).
    """
    import struct as _struct

    if not getattr(csr, "_refreshable", True) or csr.properties or (
        csr.in_edge_weight is not None
    ):
        raise ValueError(
            "refresh_csr supports unfiltered snapshots without materialized "
            "properties/weights — reload with load_csr for filtered views"
        )
    es = graph.edge_serializer
    idm = graph.idm
    st = graph.system_types
    new_epoch = graph.backend.mutation_epoch()
    keys = graph.backend.touched_since(since_epoch)
    if keys is None:
        # tracker overflowed past the snapshot: epoch rebuild
        fresh, e2 = load_csr_snapshot(graph)
        return fresh, e2
    if not keys:
        return csr, new_epoch

    btx = graph.backend.begin_transaction()
    store_tx = btx.store_tx
    store = graph.backend.edgestore
    full_q = SliceQuery(bytes([0]), bytes([4]))
    unpack_tid = _struct.Struct(">Q").unpack_from
    relidx_ids = getattr(graph, "relation_index_ids", frozenset())
    canonicalize = idm.get_canonical_vertex_id

    touched: set = set()
    alive: Dict[int, int] = {}          # vid -> label id
    new_src: List[int] = []
    new_dst: List[int] = []
    new_et: List[int] = []
    for key in keys:
        vid = idm.get_vertex_id(key)
        if not idm.is_user_vertex_id(vid):
            continue
        vid = canonicalize(vid)
        touched.add(vid)
        exists = False
        label_id = 0
        from janusgraph_tpu.storage.kcvs import KeySliceQuery as _KSQ

        for col, val in store.get_slice(_KSQ(key, full_q), store_tx):
            cat = col[0]
            if cat == 0:
                if unpack_tid(col, 1)[0] == st.EXISTS:
                    exists = True
            elif cat == 2:
                if unpack_tid(col, 1)[0] == st.VERTEX_LABEL_EDGE:
                    rc = es.parse_relation((col, val), st.type_info)
                    label_id = rc.other_vertex_id
            elif cat == 3:
                if len(col) == EDGE_COL_FIXED and not val:
                    # fixed-width fast parse
                    tid = int.from_bytes(col[1:9], "big")
                    if (
                        col[9] == int(Direction.OUT)
                        and tid not in relidx_ids
                    ):
                        new_src.append(vid)
                        new_dst.append(int.from_bytes(col[11:19], "big"))
                        new_et.append(tid)
                else:
                    rc = es.parse_relation((col, val), graph_codec_schema(graph))
                    if (
                        rc.is_edge
                        and rc.direction == Direction.OUT
                        and rc.type_id not in relidx_ids
                    ):
                        new_src.append(vid)
                        new_dst.append(int(rc.other_vertex_id))
                        new_et.append(int(rc.type_id))
        if exists:
            alive[vid] = label_id

    # old edges in vid space; drop any whose SOURCE row was touched
    # (re-derived above) — destination-side deletions always touch the
    # source row too (both cells are written per mutation)
    old_src_vid = np.repeat(csr.vertex_ids, np.diff(csr.out_indptr))
    old_dst_vid = csr.vertex_ids[csr.out_dst]
    keep = ~np.isin(old_src_vid, np.fromiter(touched, dtype=np.int64))
    old_src_vid = old_src_vid[keep]
    old_dst_vid = old_dst_vid[keep]
    old_et = (
        csr.out_edge_type[keep] if csr.out_edge_type is not None else None
    )

    removed = {v for v in touched if v not in alive}
    vertex_ids = np.unique(np.concatenate([
        csr.vertex_ids[~np.isin(
            csr.vertex_ids, np.fromiter(removed, dtype=np.int64)
        )] if removed else csr.vertex_ids,
        np.fromiter(alive.keys(), dtype=np.int64, count=len(alive)),
    ]))

    src_vid = np.concatenate([old_src_vid, np.asarray(new_src, dtype=np.int64)])
    dst_vid = np.concatenate([old_dst_vid, np.asarray(new_dst, dtype=np.int64)])
    if idm.partition_bits > 0 and _any_partitioned(idm, dst_vid):
        dst_vid = canonicalize_ids(idm, dst_vid)
    et = None
    if old_et is not None or new_et:
        et = np.concatenate([
            old_et if old_et is not None
            else np.zeros(len(old_src_vid), dtype=np.int32),
            np.asarray(new_et, dtype=np.int32),
        ])

    n = len(vertex_ids)
    si = np.searchsorted(vertex_ids, src_vid)
    di = np.searchsorted(vertex_ids, dst_vid)
    valid = (
        (si < n) & (di < n)
        & (vertex_ids[np.minimum(si, n - 1)] == src_vid)
        & (vertex_ids[np.minimum(di, n - 1)] == dst_vid)
    )
    si = si[valid].astype(np.int32)
    di = di[valid].astype(np.int32)
    if et is not None:
        et = et[valid]
    # canonical layout parity with a fresh full load: within each source row
    # the store orders edge columns by (type, other-vid)
    order = np.lexsort(
        (di, et if et is not None else np.zeros(len(si), dtype=np.int32), si)
    )
    si, di = si[order], di[order]
    if et is not None:
        et = et[order]

    # labels: retained from old where known, overridden for touched rows
    labels = None
    if csr.labels is not None or alive:
        labels = np.zeros(n, dtype=np.int64)
        if csr.labels is not None:
            pos = np.searchsorted(vertex_ids, csr.vertex_ids)
            ok = (pos < n) & (vertex_ids[np.minimum(pos, n - 1)] == csr.vertex_ids)
            labels[pos[ok]] = csr.labels[ok]
        for vid, lid in alive.items():
            i = int(np.searchsorted(vertex_ids, vid))
            labels[i] = lid

    refreshed = csr_from_edges(n, si, di, edge_types=et)
    refreshed.vertex_ids = vertex_ids
    refreshed.labels = labels
    return refreshed, new_epoch


def channel_edges(
    csr: CSRGraph, channel
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Flatten an EdgeChannel view into (src_idx, dst_idx, weight) arrays
    where messages flow src -> dst (aggregation happens at dst).

    direction "out": traversers move src->dst, so aggregation reads the
    in-CSR; "in" reverses the edges (aggregate at the source over its
    out-edges); "both" is the union. Label filtering requires the CSR to
    carry per-edge type arrays (load_csr / csr_from_edges edge_types).
    """
    parts_src: List[np.ndarray] = []
    parts_dst: List[np.ndarray] = []
    parts_w: List[np.ndarray] = []
    have_w = csr.in_edge_weight is not None or csr.out_edge_weight is not None

    def _select(src, dst, w, types):
        if channel.labels is not None:
            if types is None:
                raise ValueError(
                    "EdgeChannel with labels requires per-edge type arrays "
                    "(load the CSR with edge types)"
                )
            mask = np.isin(types, np.asarray(channel.labels, dtype=types.dtype))
            src, dst = src[mask], dst[mask]
            w = w[mask] if w is not None else None
        parts_src.append(src)
        parts_dst.append(dst)
        if have_w:
            parts_w.append(
                w if w is not None else np.ones(len(src), dtype=np.float32)
            )

    m = csr.num_edges
    if channel.direction in ("out", "both"):
        seg = np.repeat(
            np.arange(csr.num_vertices, dtype=np.int64), np.diff(csr.in_indptr)
        )
        _select(
            csr.in_src.astype(np.int64), seg, csr.in_edge_weight, csr.in_edge_type
        )
    if channel.direction in ("in", "both"):
        seg = np.repeat(
            np.arange(csr.num_vertices, dtype=np.int64), np.diff(csr.out_indptr)
        )
        _select(
            csr.out_dst.astype(np.int64), seg, csr.out_edge_weight, csr.out_edge_type
        )
    if channel.direction not in ("out", "in", "both"):
        raise ValueError(f"unknown channel direction {channel.direction!r}")
    src = np.concatenate(parts_src) if parts_src else np.empty(0, np.int64)
    dst = np.concatenate(parts_dst) if parts_dst else np.empty(0, np.int64)
    w = np.concatenate(parts_w) if have_w and parts_w else None
    return src, dst, w
