"""Incremental delta-CSR: O(delta) snapshot refresh and streaming-fresh
analytics without the repack (ROADMAP #4).

Every OLAP run and every spillover snapshot refresh used to pay a full
scan + CSR pack — r05 measured transfer+pack at 5.6 s at s20 against 75 ms
per superstep, so preprocessing dwarfed the compute it fed (exactly the
cost hardware-assisted propagation blocking, arXiv 2011.08451, targets).
This module makes the snapshot incremental:

- **Change capture** (:class:`ChangeCapture`): the WAL/existence-cell
  machinery from PR 3 already sees every mutation —
  ``BackendTransaction.commit`` taps the committed edgestore batch into a
  bounded per-graph capture ring. Decoding is vectorized through the same
  fixed-width bulk edge decoder the scan loader uses, so a bulk-load
  commit costs one numpy pass, not a per-cell Python loop. Records:
  edge adds, edge deletes (the tombstone lane), vertex add/remove.

- **Delta overlay** (:class:`DeltaOverlay` -> :class:`OverlayView`):
  pending records net out (multiset counting — a delete cancels a
  pending add of the same ``(src, dst, type)`` triple) into pow2-tiered
  COO lanes over the base CSR's index space: an **add lane**, a
  **tombstone lane**, and — for the MIN/MAX family, where a deleted
  edge's contribution cannot be subtracted — per-**dirty-row live
  lanes** that re-aggregate a tombstoned destination's surviving base
  edges. New vertices extend the domain in a pow2 ``vcap`` tier appended
  after the base rows (base indices stay stable, so the device-resident
  base packs are reused untouched).

- **Fused consumption** (:func:`fused_delta_aggregate`): executors run
  their base aggregation over the unchanged base pack (messages sliced
  to the base rows so the pack's sentinel slot stays the identity), then
  merge the delta lanes through the same ``_segment_combine`` contract
  as the blocked exchange's bins (PR 9 — a delta is just another bin
  source):

    SUM:      out = base + segsum(adds) - segsum(tombstones)
    MIN/MAX:  out = op(where(dirty, seg_op(live), base), seg_op(adds))

  MIN-family results are **bitwise-identical** to a freshly repacked CSR
  (min is exact and order-independent over the identical edge multiset);
  SUM results are bitwise-identical to the numpy replay oracle
  (:func:`replay_fused_aggregate` — ``np.add.at`` == XLA CPU scatter,
  the PR 9 contract) and float-close to the repack.

- **Materialization** (:func:`materialize`): fold the overlay into new
  CSR arrays with the SAME canonical edge layout a fresh load produces
  (lexsort by (src, type, dst) — refresh_csr parity), with **zero store
  reads**: unlike ``refresh_csr``'s whole-row re-derivation, the records
  alone carry the delta. This is the spillover snapshot's refresh path
  and the warm ``GraphComputer.submit()`` path when the overlay is too
  large (or the program too exotic) to consume fused.

- **Compaction** (:class:`DeltaSnapshot`): the overlay folds back into
  the base pack once its depth crosses an autotuner-decided threshold
  (``olap/autotune.decide_delta``; override ``computer.
  delta-compact-threshold``), off the superstep path, with the usual
  tmp+rename discipline when ``computer.delta-snapshot-path`` persists
  the pack. Every compaction is a ``delta_compact`` flight event and the
  ``olap.delta.compactions`` counter.

- **Sharded routing** (:func:`route_overlay`): each delta record routes
  to the shard owning its aggregation-side (destination) row through the
  same contiguous ``dst // Np`` coupling as ``multihost.
  host_shard_range`` / the blocked halo plan, so a distributed refresh
  applies only each host's slice.

Scope guards (all fall back to a full reload, never to wrong numbers):
weighted or filtered snapshots, capture overflow, decode surprises, and
programs with typed edge channels / sddmm message modes refuse the
overlay.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.vertex_program import Combiner

#: fused-domain extra-vertex capacity tier ladder: next pow2 (0 = none).
#: Named per the JG301 delta vocabulary — overlay tiers must be pow2 so
#: one compiled superstep executable serves every overlay that fits.
def overlay_tier(n: int) -> int:
    if n <= 0:
        return 0
    return 1 << max(0, int(n) - 1).bit_length()


# ---------------------------------------------------------------------------
# Change capture
# ---------------------------------------------------------------------------

class ChangeCapture:
    """Bounded per-graph ring of committed graph-structure deltas.

    Fed from ``BackendTransaction.commit`` (via ``Backend.
    register_change_capture``) with the committed edgestore mutation
    batch; batches decode vectorized and append in epoch order. Consumers
    call :meth:`records_since(epoch)`; ``None`` means the capture cannot
    serve that epoch (ring overflow past it, or a cell the decoder could
    not classify) and the caller must fall back to a full reload."""

    def __init__(self, graph, limit: int = 1 << 16):
        self.graph = graph
        self.limit = int(limit)
        self._lock = threading.Lock()
        self._batches: deque = deque()  # graphlint: disable=JG206 -- bounded by the record-count prune below (limit records, oldest batches dropped on overflow)
        self._count = 0
        #: records with epoch <= floor may have been dropped/undecodable
        self._floor = 0
        #: durable sinks fed every decoded batch (storage/cdc.py CDCLog.
        #: append); poison forwards as (epoch, None) so the sink can
        #: record the un-servable range honestly
        self._sinks: List = []

    def add_sink(self, fn) -> None:
        """Register a ``fn(epoch, batch_or_None)`` durable sink. Sinks
        ride the commit path, so failures are swallowed and counted —
        capture (and the commit) must never fail because a sink did."""
        with self._lock:
            self._sinks.append(fn)

    def _feed_sinks(self, epoch: int, batch: Optional[dict]) -> None:
        for fn in self._sinks:
            try:
                fn(epoch, batch)
            except Exception:  # noqa: BLE001 - never fail a commit
                from janusgraph_tpu.observability import registry

                registry.counter("olap.delta.sink_errors").inc()

    # -- write side ---------------------------------------------------------
    def on_commit(self, epoch: int, edge_rows: Dict[bytes, object]) -> None:
        """Called with the committed edgestore row mutations (under the
        backend's epoch lock, so batches land in epoch order)."""
        try:
            batch = self._decode(edge_rows)
        except Exception:  # noqa: BLE001 - capture must never fail a commit
            batch = None
        with self._lock:
            if batch is None:
                # poison: snapshots at or before this epoch cannot be
                # served incrementally any more
                self._batches.clear()
                self._count = 0
                self._floor = epoch
                from janusgraph_tpu.observability import registry

                registry.counter("olap.delta.capture_poisoned").inc()
                self._feed_sinks(epoch, None)
                return
            if not batch["n"]:
                return
            self._batches.append((epoch, batch))
            self._count += batch["n"]
            while self._count > self.limit and self._batches:
                e0, b0 = self._batches.popleft()
                self._count -= b0["n"]
                self._floor = e0
            self._feed_sinks(epoch, batch)

    def _decode(self, edge_rows) -> Optional[dict]:
        """One committed batch -> vid-space record arrays. Returns None
        when any cell resists classification (the capture then refuses to
        serve epochs at or before this batch — correctness over
        freshness)."""
        import struct as _struct

        from janusgraph_tpu.core.codecs import Direction, EDGE_COL_FIXED

        g = self.graph
        idm = g.idm
        st = g.system_types
        es = g.edge_serializer
        relidx = getattr(g, "relation_index_ids", frozenset())
        unpack_tid = _struct.Struct(">Q").unpack_from

        add_cols: List[bytes] = []
        add_vids: List[int] = []
        del_cols: List[bytes] = []
        del_vids: List[int] = []
        slow_add: List[Tuple[int, int, int]] = []
        slow_del: List[Tuple[int, int, int]] = []
        v_add: Dict[int, int] = {}
        v_del: List[int] = []

        def _slow(vid, col, val):
            from janusgraph_tpu.olap.csr import graph_codec_schema

            rc = es.parse_relation((col, val), graph_codec_schema(g))
            if not rc.is_edge or rc.direction != Direction.OUT:
                return None
            if rc.type_id in relidx:
                return None
            return (vid, int(rc.other_vertex_id), int(rc.type_id))

        for key, m in edge_rows.items():
            vid = idm.get_vertex_id(key)
            if not idm.is_user_vertex_id(vid):
                continue
            vid = idm.get_canonical_vertex_id(vid)
            for entry in m.additions:
                col, val = entry[0], entry[1]
                cat = col[0]
                if cat == 3:
                    if len(col) == EDGE_COL_FIXED:
                        add_cols.append(col)
                        add_vids.append(vid)
                    else:
                        t = _slow(vid, col, val)
                        if t is not None:
                            slow_add.append(t)
                elif cat == 0:
                    if unpack_tid(col, 1)[0] == st.EXISTS:
                        v_add.setdefault(vid, 0)
                elif cat == 2:
                    if unpack_tid(col, 1)[0] == st.VERTEX_LABEL_EDGE:
                        rc = es.parse_relation((col, val), st.type_info)
                        v_add[vid] = int(rc.other_vertex_id)
            for col in m.deletions:
                cat = col[0]
                if cat == 3:
                    if len(col) == EDGE_COL_FIXED:
                        del_cols.append(col)
                        del_vids.append(vid)
                    else:
                        # a deletion carries no value; the OUT-edge
                        # identity fields all live in the column, so the
                        # codec parse still resolves them
                        t = _slow(vid, col, b"")
                        if t is not None:
                            slow_del.append(t)
                elif cat == 0:
                    if unpack_tid(col, 1)[0] == st.EXISTS:
                        v_del.append(vid)

        def _bulk(cols, vids, slow):
            if cols:
                tids, dirs, others, _rels = es.bulk_decode_edges(cols)
                owner = np.asarray(vids, dtype=np.int64)
                mask = dirs == int(Direction.OUT)
                if relidx:
                    mask &= ~np.isin(
                        tids, np.fromiter(relidx, dtype=np.int64)
                    )
                src = owner[mask]
                dst = others[mask]
                et = tids[mask]
            else:
                src = dst = et = np.empty(0, np.int64)
            if slow:
                s = np.asarray(slow, dtype=np.int64).reshape(-1, 3)
                src = np.concatenate([src, s[:, 0]])
                dst = np.concatenate([dst, s[:, 1]])
                et = np.concatenate([et, s[:, 2]])
            return (
                np.asarray(src, dtype=np.int64),
                np.asarray(dst, dtype=np.int64),
                np.asarray(et, dtype=np.int64),
            )

        a_src, a_dst, a_et = _bulk(add_cols, add_vids, slow_add)
        d_src, d_dst, d_et = _bulk(del_cols, del_vids, slow_del)
        n = (
            len(a_src) + len(d_src) + len(v_add) + len(v_del)
        )
        return {
            "n": n,
            "add": (a_src, a_dst, a_et),
            "del": (d_src, d_dst, d_et),
            "v_add": dict(v_add),
            "v_del": list(v_del),
        }

    # -- read side ----------------------------------------------------------
    def records_since(self, epoch: int) -> Optional[List[dict]]:
        with self._lock:
            if epoch < self._floor:
                return None
            return [b for e, b in self._batches if e > epoch]

    def slice_since(self, epoch: int) -> Optional[Tuple[List[dict], int]]:
        """(batches past `epoch`, anchor epoch) — the anchor is the max
        epoch actually CONSUMED, so a consumer that re-anchors there can
        never double-apply a record committed during the read."""
        with self._lock:
            if epoch < self._floor:
                return None
            batches = [(e, b) for e, b in self._batches if e > epoch]
            upto = max((e for e, _ in batches), default=epoch)
            return [b for _, b in batches], upto

    def depth_since(self, epoch: int) -> Optional[int]:
        """Pending record count past `epoch` — the overlay-lag signal the
        staleness gauge tracks. None = cannot serve (overflow)."""
        with self._lock:
            if epoch < self._floor:
                return None
            return sum(b["n"] for e, b in self._batches if e > epoch)


# ---------------------------------------------------------------------------
# Delta overlay (vid space)
# ---------------------------------------------------------------------------

@dataclass
class DeltaOverlay:
    """Netted graph-structure delta in graph-id space: the multiset
    difference between the live graph and a base snapshot."""

    #: net edge additions, one row per surviving instance
    add: np.ndarray          # (a, 3) int64 (src vid, dst vid, type id)
    #: net edge deletions against the BASE multiset
    tomb: np.ndarray         # (t, 3) int64
    new_vertices: Dict[int, int] = field(default_factory=dict)
    removed: frozenset = frozenset()

    @property
    def size(self) -> int:
        return (
            len(self.add) + len(self.tomb)
            + len(self.new_vertices) + len(self.removed)
        )

    @classmethod
    def from_batches(cls, batches: List[dict]) -> "DeltaOverlay":
        """Net the capture batches: counts of adds minus deletes per
        (src, dst, type) triple — positive nets are the add lane,
        negative nets the tombstone lane (multiset counting is
        order-independent; the final multiset is base + adds - dels)."""
        adds = [b["add"] for b in batches]
        dels = [b["del"] for b in batches]

        def _stack(parts):
            if not parts or not any(len(p[0]) for p in parts):
                return np.empty((0, 3), dtype=np.int64)
            return np.stack([
                np.concatenate([p[i] for p in parts])
                for i in range(3)
            ], axis=1)

        a = _stack(adds)
        d = _stack(dels)
        if len(a) or len(d):
            uni, inv = np.unique(
                np.concatenate([a, d]), axis=0, return_inverse=True
            )
            cnt = np.bincount(inv[: len(a)], minlength=len(uni)).astype(
                np.int64
            ) - np.bincount(inv[len(a):], minlength=len(uni))
            net_add = np.repeat(
                uni[cnt > 0], cnt[cnt > 0], axis=0
            )
            net_del = np.repeat(
                uni[cnt < 0], -cnt[cnt < 0], axis=0
            )
        else:
            net_add = net_del = np.empty((0, 3), dtype=np.int64)
        # vertex records: last state wins across batches (epoch order)
        vfinal: Dict[int, Optional[int]] = {}
        for b in batches:
            for vid, label in b["v_add"].items():
                vfinal[vid] = label
            for vid in b["v_del"]:
                vfinal[vid] = None
        new_vertices = {
            vid: lab for vid, lab in vfinal.items() if lab is not None
        }
        removed = frozenset(
            vid for vid, lab in vfinal.items() if lab is None
        )
        return cls(
            add=net_add, tomb=net_del,
            new_vertices=new_vertices, removed=removed,
        )


def overlay_since(graph, epoch: int) -> Optional[Tuple[DeltaOverlay, int]]:
    """(pending overlay past `epoch`, anchor epoch) from the graph's
    change capture, or None when the capture cannot serve it (disabled /
    overflow / poisoned decode)."""
    cap = getattr(graph, "change_capture", None)
    if cap is None:
        return None
    sl = cap.slice_since(epoch)
    if sl is None:
        return None
    batches, upto = sl
    return DeltaOverlay.from_batches(batches), upto


# ---------------------------------------------------------------------------
# Materialization: overlay -> new CSR arrays, zero store reads
# ---------------------------------------------------------------------------

def _key_rank(idm, vertex_ids: np.ndarray) -> np.ndarray:
    """Per-vertex rank in STORE-KEY order (partition-prefixed row keys,
    core/ids.get_key) — the order an ordered scan visits rows in, and
    therefore the fresh load's global edge order. Vectorized twin of
    IDManager.get_key over the snapshot's (user-vertex) id vector."""
    from janusgraph_tpu.core.ids import TOTAL_BITS

    vids = np.asarray(vertex_ids, dtype=np.int64)
    pb = idm.partition_bits
    partition = (vids >> 3) & ((1 << pb) - 1)
    rest = ((vids >> (3 + pb)) << 3) | (vids & 0b111)
    key_int = (
        (partition.astype(np.uint64) << np.uint64(TOTAL_BITS - pb))
        | rest.astype(np.uint64)
    )
    rank = np.empty(len(vids), dtype=np.int64)
    rank[np.argsort(key_int, kind="stable")] = np.arange(len(vids))
    return rank


def materialize(csr, overlay: DeltaOverlay, idm=None):
    """Fold the overlay into fresh CSR arrays with the SAME canonical edge
    layout a full reload produces — from the captured records alone: zero
    store reads, unlike refresh_csr's whole-row re-derivation. With `idm`
    the merged edges sort in store-key scan order (key rank of the source
    row, then (type, destination) — exactly the ordered scan's layout),
    so executor runs over the materialized arrays are BITWISE-identical
    to runs over a repacked CSR for every monoid; without it, source-
    index order (row-set equal, within-row order monoid-irrelevant).
    Supports unfiltered, weightless snapshots only (the same envelope as
    refresh_csr)."""
    from janusgraph_tpu.olap.csr import csr_from_edges

    if csr.in_edge_weight is not None or csr.properties:
        raise ValueError(
            "delta materialize supports unfiltered snapshots without "
            "materialized properties/weights"
        )
    vids = csr.vertex_ids
    removed = overlay.removed
    extra = np.setdiff1d(
        np.fromiter(
            overlay.new_vertices.keys(), dtype=np.int64,
            count=len(overlay.new_vertices),
        ),
        vids,
    ) if overlay.new_vertices else np.empty(0, np.int64)
    keep_v = (
        ~np.isin(vids, np.fromiter(removed, dtype=np.int64))
        if removed else np.ones(len(vids), dtype=bool)
    )
    vertex_ids = np.unique(np.concatenate([vids[keep_v], extra]))
    n = len(vertex_ids)

    src_vid = np.repeat(vids, np.diff(csr.out_indptr)).astype(np.int64)
    dst_vid = vids[csr.out_dst].astype(np.int64)
    et = (
        csr.out_edge_type.astype(np.int64)
        if csr.out_edge_type is not None
        else np.zeros(len(src_vid), dtype=np.int64)
    )
    if len(overlay.tomb):
        # multiset subtraction: drop the first `tomb count` instances of
        # each (src, dst, type) token (same trick as spillover's
        # patched_csr — parallel edges are count-equivalent)
        m = len(src_vid)
        trip = np.stack([src_vid, dst_vid, et], axis=1)
        _, inv = np.unique(
            np.concatenate([trip, overlay.tomb]), axis=0,
            return_inverse=True,
        )
        etok, dtok = inv[:m], inv[m:]
        del_counts = np.bincount(dtok, minlength=int(inv.max()) + 1)
        order = np.argsort(etok, kind="stable")
        st = etok[order]
        first = np.searchsorted(st, st, side="left")
        rank = np.arange(m) - first
        keep = np.ones(m, dtype=bool)
        keep[order[rank < del_counts[st]]] = False
        src_vid, dst_vid, et = src_vid[keep], dst_vid[keep], et[keep]
    if len(overlay.add):
        src_vid = np.concatenate([src_vid, overlay.add[:, 0]])
        dst_vid = np.concatenate([dst_vid, overlay.add[:, 1]])
        et = np.concatenate([et, overlay.add[:, 2]])

    si = np.searchsorted(vertex_ids, src_vid)
    di = np.searchsorted(vertex_ids, dst_vid)
    valid = (
        (si < n) & (di < n)
        & (vertex_ids[np.minimum(si, n - 1)] == src_vid)
        & (vertex_ids[np.minimum(di, n - 1)] == dst_vid)
    )
    si = si[valid].astype(np.int32)
    di = di[valid].astype(np.int32)
    et = et[valid]
    # canonical layout parity with a fresh full load: the scan visits
    # rows in store-key order, and BOTH derived CSRs inherit the input's
    # global edge order through the stable sorts in native.build_csr
    src_key = _key_rank(idm, vertex_ids)[si] if idm is not None else si
    order = np.lexsort((di, et, src_key))
    si, di, et = si[order], di[order], et[order]

    labels = None
    if csr.labels is not None or overlay.new_vertices:
        labels = np.zeros(n, dtype=np.int64)
        if csr.labels is not None:
            pos = np.searchsorted(vertex_ids, vids)
            ok = (pos < n) & (
                vertex_ids[np.minimum(pos, n - 1)] == vids
            )
            labels[pos[ok]] = csr.labels[ok]
        for vid, lid in overlay.new_vertices.items():
            i = int(np.searchsorted(vertex_ids, vid))
            if i < n and vertex_ids[i] == vid:
                labels[i] = lid

    has_et = csr.out_edge_type is not None or len(overlay.add)
    out = csr_from_edges(
        n, si, di,
        edge_types=et.astype(np.int32) if has_et else None,
    )
    out.vertex_ids = vertex_ids
    out.labels = labels
    out._refreshable = getattr(csr, "_refreshable", True)
    return out


# ---------------------------------------------------------------------------
# Overlay view (index space): the fused-superstep consumable
# ---------------------------------------------------------------------------

class OverlayView:
    """The overlay translated into the base snapshot's index space, with
    pow2-tiered lane capacities — the static-shape pytree a compiled
    superstep consumes fused with the base pack.

    Domain layout (base indices stay stable so device packs are reused):
      [0, n_base)              base snapshot rows
      [n_base, n_base+n_extra) new vertices, in sorted-vid order
      [n_real, n_pad)          padding up to the vcap tier (inactive)
    """

    def __init__(self, csr, overlay: DeltaOverlay, max_lane_cells: int = 1 << 16):
        self.csr = csr
        self.overlay = overlay
        vids = csr.vertex_ids
        nb = len(vids)
        self.n_base = nb
        extra = np.setdiff1d(
            np.fromiter(
                overlay.new_vertices.keys(), dtype=np.int64,
                count=len(overlay.new_vertices),
            ),
            vids,
        ) if overlay.new_vertices else np.empty(0, np.int64)
        self.extra_ids = extra
        self.n_extra = len(extra)
        self.n_real = nb + self.n_extra
        self.vcap = overlay_tier(self.n_extra)
        self.n_pad = nb + self.vcap
        self.vertex_ids = np.concatenate([vids, extra])

        def _idx(v):
            """vid array -> fused index (or -1 when unknown)."""
            v = np.asarray(v, dtype=np.int64)
            i = np.searchsorted(vids, v)
            base_ok = (i < nb) & (vids[np.minimum(i, nb - 1)] == v)
            out = np.where(base_ok, i, -1)
            if self.n_extra:
                j = np.searchsorted(extra, v)
                ex_ok = (j < self.n_extra) & (
                    extra[np.minimum(j, self.n_extra - 1)] == v
                )
                out = np.where(ex_ok & ~base_ok, nb + j, out)
            return out.astype(np.int64)

        a = overlay.add
        asrc = _idx(a[:, 0]) if len(a) else np.empty(0, np.int64)
        adst = _idx(a[:, 1]) if len(a) else np.empty(0, np.int64)
        ok = (asrc >= 0) & (adst >= 0)
        self.add_src = asrc[ok]
        self.add_dst = adst[ok]
        self.add_et = a[ok, 2] if len(a) else np.empty(0, np.int64)
        t = overlay.tomb
        tsrc = _idx(t[:, 0]) if len(t) else np.empty(0, np.int64)
        tdst = _idx(t[:, 1]) if len(t) else np.empty(0, np.int64)
        tok = (tsrc >= 0) & (tdst >= 0) & (tsrc < nb) & (tdst < nb)
        self.tomb_src = tsrc[tok]
        self.tomb_dst = tdst[tok]
        # removed base rows -> inactive slots
        rm = (
            _idx(np.fromiter(
                overlay.removed, dtype=np.int64, count=len(overlay.removed)
            ))
            if overlay.removed else np.empty(0, np.int64)
        )
        self.removed_idx = rm[(rm >= 0) & (rm < nb)]
        self.max_lane_cells = int(max_lane_cells)
        #: capture anchor: max epoch folded into this view (set by the
        #: snapshot holder that built it)
        self.upto_epoch: Optional[int] = None
        self._lanes: Dict[bool, Optional[dict]] = {}
        self._device: Dict[Tuple, dict] = {}
        self._fused_degrees = None

    # -- degrees / activity (shared by both executors' fused views) ---------
    def fused_degrees(self):
        """(out_degree, in_degree, active) over [0, n_pad): base degrees
        patched by the lanes, extras from the add lane, padding zero.
        Integer-valued — bitwise-equal to the repacked CSR's degrees."""
        if self._fused_degrees is not None:
            return self._fused_degrees
        csr = self.csr
        nb, npad = self.n_base, self.n_pad
        outd = np.zeros(npad, dtype=np.int64)
        ind = np.zeros(npad, dtype=np.int64)
        outd[:nb] = np.diff(csr.out_indptr)
        ind[:nb] = np.diff(csr.in_indptr)
        np.subtract.at(outd, self.tomb_src, 1)
        np.subtract.at(ind, self.tomb_dst, 1)
        np.add.at(outd, self.add_src, 1)
        np.add.at(ind, self.add_dst, 1)
        active = np.zeros(npad, dtype=np.float64)
        active[: self.n_real] = 1.0
        if len(self.removed_idx):
            active[self.removed_idx] = 0.0
        self._fused_degrees = (
            np.maximum(outd, 0).astype(np.int32),
            np.maximum(ind, 0).astype(np.int32),
            active,
        )
        return self._fused_degrees

    @property
    def num_edges_real(self) -> int:
        return self.csr.num_edges - len(self.tomb_src) + len(self.add_src)

    @property
    def num_vertices_real(self) -> int:
        return self.n_real - len(self.removed_idx)

    @property
    def depth(self) -> int:
        return self.overlay.size

    # -- lanes --------------------------------------------------------------
    def lanes(self, undirected: bool) -> Optional[dict]:
        """Padded COO lanes for one aggregation orientation (the default
        in-CSR view, or the symmetric closure when `undirected`). None
        when the lanes would exceed max_lane_cells (a tombstoned hub row
        makes the live lane O(degree)) — the caller materializes
        instead."""
        if undirected in self._lanes:
            return self._lanes[undirected]
        lanes = self._build_lanes(undirected)
        self._lanes[undirected] = lanes
        return lanes

    def _build_lanes(self, undirected: bool) -> Optional[dict]:
        csr = self.csr
        npad = self.n_pad
        # aggregation-side (dst) adds; symmetric closure doubles the lanes
        a_src = self.add_src
        a_dst = self.add_dst
        t_src = self.tomb_src
        t_dst = self.tomb_dst
        if undirected:
            a_src = np.concatenate([a_src, self.add_dst])
            a_dst = np.concatenate([a_dst, self.add_src])
            t_src = np.concatenate([t_src, self.tomb_dst])
            t_dst = np.concatenate([t_dst, self.tomb_src])

        # MIN-family dirty rows: every destination with a tombstoned
        # incoming edge re-aggregates its surviving base edges via the
        # live lane (adds ride the add lane; min(x, x) = x makes the
        # double-merge of adds into a dirty row exact)
        dirty_rows = np.unique(t_dst)
        live_src_parts: List[np.ndarray] = []
        live_dst_parts: List[np.ndarray] = []
        in_indptr, in_src = csr.in_indptr, csr.in_src
        out_indptr, out_dst = csr.out_indptr, csr.out_dst

        def _survivors(srcs, rm):
            """Base neighbors minus the tombstoned multiset (one removal
            per tombstone instance — parallel edges with the same source
            are count-equivalent for aggregation)."""
            if not len(rm):
                return np.asarray(srcs, dtype=np.int64)
            srcs = np.sort(np.asarray(srcs, dtype=np.int64))
            keep = np.ones(len(srcs), dtype=bool)
            vals, cnts = np.unique(np.asarray(rm, dtype=np.int64),
                                   return_counts=True)
            for v, c in zip(vals, cnts):
                lo = int(np.searchsorted(srcs, v, side="left"))
                hi = int(np.searchsorted(srcs, v, side="right"))
                keep[lo: min(hi, lo + int(c))] = False
            return srcs[keep]

        # group tombstones by their aggregation row once
        if len(dirty_rows):
            order = np.argsort(t_dst, kind="stable")
            td_sorted = t_dst[order]
            ts_sorted = t_src[order]
            bounds = np.searchsorted(td_sorted, dirty_rows, side="left")
            bounds_hi = np.searchsorted(td_sorted, dirty_rows, side="right")
            for r, lo, hi in zip(dirty_rows, bounds, bounds_hi):
                r = int(r)
                rm = ts_sorted[lo:hi]
                neigh = in_src[in_indptr[r]: in_indptr[r + 1]].astype(
                    np.int64
                ) if r < self.n_base else np.empty(0, np.int64)
                if undirected and r < self.n_base:
                    # symmetric closure: out-neighbors of the row too —
                    # tombstones in t_* already carry both orientations,
                    # but the rm list here mixes them; subtract the
                    # multiset against the COMBINED neighbor list
                    neigh = np.concatenate([
                        neigh,
                        out_dst[
                            out_indptr[r]: out_indptr[r + 1]
                        ].astype(np.int64),
                    ])
                surv = _survivors(neigh, rm)
                live_src_parts.append(surv)
                live_dst_parts.append(
                    np.full(len(surv), r, dtype=np.int64)
                )
        live_src = (
            np.concatenate(live_src_parts)
            if live_src_parts else np.empty(0, np.int64)
        )
        live_dst = (
            np.concatenate(live_dst_parts)
            if live_dst_parts else np.empty(0, np.int64)
        )

        acap = overlay_tier(len(a_src))
        tcap = overlay_tier(len(t_src))
        lcap = overlay_tier(len(live_src))
        if acap + tcap + lcap > self.max_lane_cells:
            return None

        def _pad(arr, cap):
            out = np.full(cap, npad, dtype=np.int32)  # sentinel = n_pad
            out[: len(arr)] = arr
            return out

        dirty = np.zeros(npad, dtype=np.float32)
        if len(dirty_rows):
            dirty[dirty_rows] = 1.0
        return {
            "add_src": _pad(a_src, acap),
            "add_dst": _pad(a_dst, acap),
            "tomb_src": _pad(t_src, tcap),
            "tomb_dst": _pad(t_dst, tcap),
            "live_src": _pad(live_src, lcap),
            "live_dst": _pad(live_dst, lcap),
            "dirty": dirty,
            # static metadata (not shipped as traced leaves)
            "_meta": {
                "n_base": self.n_base,
                "n_pad": npad,
                "acap": acap,
                "tcap": tcap,
                "lcap": lcap,
            },
        }

    def sig(self, undirected: bool) -> Optional[Tuple]:
        """Static compile signature of the fused variant — part of every
        compiled-executable cache key."""
        lanes = self.lanes(undirected)
        if lanes is None:
            return None
        m = lanes["_meta"]
        return (
            m["n_base"], m["n_pad"], m["acap"], m["tcap"], m["lcap"],
            bool(undirected),
        )

    def device_args(self, jnp, undirected: bool):
        """The lane pytree as device arrays (cached) — shipped as jit
        ARGUMENTS like the base pack, never closed over."""
        key = ("dev", bool(undirected))
        cached = self._device.get(key)
        if cached is not None:
            return cached
        lanes = self.lanes(undirected)
        if lanes is None:
            return None
        dev = {
            k: jnp.asarray(v)
            for k, v in lanes.items() if not k.startswith("_")
        }
        self._device[key] = dev
        return dev


# graphlint: traced -- the fused delta merge of compiled superstep bodies
def fused_delta_aggregate(xp, lanes, meta, outgoing, base_agg, op):
    """Merge the delta lanes into a base aggregation — the fused
    base+delta superstep (module docstring: SUM subtracts tombstones,
    MIN/MAX replaces dirty rows from the live lane). xp-generic: the CPU
    executor replays the identical arithmetic in numpy, which is also the
    SUM contract's replay oracle."""
    from janusgraph_tpu.olap.kernels import _segment_combine

    identity = Combiner.IDENTITY[op]
    nb, npad = meta["n_base"], meta["n_pad"]
    tail = npad - base_agg.shape[0]
    if tail:
        pad = xp.full(
            (tail,) + tuple(base_agg.shape[1:]), identity,
            dtype=base_agg.dtype,
        )
        base = xp.concatenate([base_agg, pad], axis=0)
    else:
        base = base_agg
    # sentinel slot: padded lane entries gather the identity and scatter
    # into the dropped row npad
    pad_shape = (1,) + tuple(outgoing.shape[1:])
    msgs_ext = xp.concatenate(
        [outgoing, xp.full(pad_shape, identity, dtype=outgoing.dtype)],
        axis=0,
    )
    add = _segment_combine(
        xp, op, msgs_ext[lanes["add_src"]], lanes["add_dst"], npad + 1
    )[:npad]
    if op == Combiner.SUM:
        sub = _segment_combine(
            xp, op, msgs_ext[lanes["tomb_src"]], lanes["tomb_dst"],
            npad + 1,
        )[:npad]
        return base + add - sub
    live = _segment_combine(
        xp, op, msgs_ext[lanes["live_src"]], lanes["live_dst"], npad + 1
    )[:npad]
    dirty = lanes["dirty"]
    if base.ndim == 2:
        dirty = dirty[:, None]
    merged = xp.where(dirty > 0, live, base)
    if op == Combiner.MIN:
        return xp.minimum(merged, add)
    return xp.maximum(merged, add)


def replay_fused_aggregate(lanes, meta, outgoing, base_agg, op):
    """Numpy replay oracle for the fused merge — np.add.at / ufunc.at is
    bitwise-identical to the XLA CPU scatter (the PR 9 contract), and
    fused_delta_aggregate with xp=numpy routes through the same
    _segment_combine ufunc path, so this IS the oracle arithmetic."""
    return fused_delta_aggregate(np, lanes, meta, outgoing, base_agg, op)


# ---------------------------------------------------------------------------
# Fused host view (program-facing graph facade over base + overlay)
# ---------------------------------------------------------------------------

class FusedHostView:
    """CSRGraph-shaped facade for a base snapshot + overlay: programs see
    the REAL vertex/edge counts and fused degree/active arrays sized to
    the padded domain, while the base index arrays stay untouched for the
    base aggregation (the executor slices messages to the base rows).
    Numpy arrays — the CPU executor consumes it directly, the TPU
    executor wraps fields to device."""

    def __init__(self, view: OverlayView):
        self._ov = view
        csr = view.csr
        outd, ind, active = view.fused_degrees()
        self.num_vertices = view.num_vertices_real
        self.local_num_vertices = view.n_pad
        self.global_offset = 0
        self.num_edges = view.num_edges_real
        self.out_degree = outd
        self.in_degree = ind
        self.active = active
        self.vertex_ids = view.vertex_ids
        # base index arrays (for the executors' base aggregation only)
        self.in_indptr = csr.in_indptr
        self.in_src = csr.in_src
        self.out_indptr = csr.out_indptr
        self.out_dst = csr.out_dst
        self.in_edge_weight = None
        self.out_edge_weight = None
        self.in_edge_type = csr.in_edge_type
        self.out_edge_type = csr.out_edge_type
        self.properties = {}
        self.labels = None

    def index_of(self, vid: int) -> int:
        v = self._ov.vertex_ids
        i = np.nonzero(v == vid)[0]
        if not len(i):
            raise KeyError(f"vertex id {vid} not in fused snapshot")
        return int(i[0])

    def id_of(self, index: int) -> int:
        return int(self._ov.vertex_ids[index])


# ---------------------------------------------------------------------------
# Sharded routing (host_shard_range coupling)
# ---------------------------------------------------------------------------

def route_overlay(view: OverlayView, num_shards: int) -> List[dict]:
    """Partition the overlay's index-space records by OWNER SHARD of the
    aggregation-side (destination) row — the same contiguous
    ``dst // Np`` coupling the sharded executor's layout and
    ``multihost.host_shard_range`` use, so a distributed refresh routes
    each record to the host that owns its rows without any O(E)
    redistribution."""
    Np = -(-max(view.n_pad, 1) // num_shards)
    out = []
    for s in range(num_shards):
        lo, hi = s * Np, (s + 1) * Np
        am = (view.add_dst >= lo) & (view.add_dst < hi)
        tm = (view.tomb_dst >= lo) & (view.tomb_dst < hi)
        out.append({
            "shard": s,
            "row_range": (lo, min(hi, view.n_pad)),
            "add_src": view.add_src[am],
            "add_dst": view.add_dst[am],
            "tomb_src": view.tomb_src[tm],
            "tomb_dst": view.tomb_dst[tm],
        })
    return out


def route_for_host(
    view: OverlayView,
    num_shards: int,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> dict:
    """The concatenated routed records for THIS host's shard span
    (multihost.host_shard_range) — what a distributed snapshot refresh
    applies to its local blocks."""
    from janusgraph_tpu.parallel.multihost import host_shard_range

    lo_s, hi_s = host_shard_range(num_shards, process_id, num_processes)
    routed = route_overlay(view, num_shards)[lo_s:hi_s]
    return {
        "shards": (lo_s, hi_s),
        "add_src": np.concatenate(
            [r["add_src"] for r in routed]
        ) if routed else np.empty(0, np.int64),
        "add_dst": np.concatenate(
            [r["add_dst"] for r in routed]
        ) if routed else np.empty(0, np.int64),
        "tomb_src": np.concatenate(
            [r["tomb_src"] for r in routed]
        ) if routed else np.empty(0, np.int64),
        "tomb_dst": np.concatenate(
            [r["tomb_dst"] for r in routed]
        ) if routed else np.empty(0, np.int64),
    }


# ---------------------------------------------------------------------------
# Warm snapshot + compaction
# ---------------------------------------------------------------------------

class DeltaSnapshot:
    """Per-graph warm snapshot: base CSR + capture epoch + compaction
    policy. GraphComputer.submit() consults it to skip the store scan;
    the spillover planner shares the capture but keeps its own snapshot
    (its CSR carries no base-pack device residency)."""

    def __init__(self, graph):
        self.graph = graph
        cfg = graph.config
        self.max_overlay = int(cfg.get("computer.delta-max-overlay"))
        self.max_lane_cells = int(cfg.get("computer.delta-max-lane-cells"))
        self.compact_threshold = int(
            cfg.get("computer.delta-compact-threshold")
        )
        self.snapshot_path = cfg.get("computer.delta-snapshot-path") or None
        self._lock = threading.RLock()
        self.csr = None
        self.epoch = -1
        self._decision = None
        #: base-pack generation: bumped whenever `csr` is replaced (cold
        #: load, compaction, adopt, warm-up install) — the executor
        #: cache's invalidation edge
        self.generation = 0
        #: warm-submit executor cache (the PR 14 REMAINING): device-
        #: resident packs + compiled executables keyed by (executor kind,
        #: constructor signature), reused across submits over ONE base
        #: pack; cleared on every generation bump
        self._executors: Dict[Tuple, object] = {}

    # ------------------------------------------------------------- snapshot
    def acquire(self):
        """(csr, overlay_view | None, info): the current base snapshot
        plus the pending overlay. A cold cache (or a capture that cannot
        serve the cached epoch) pays one full scan; afterwards every
        acquire is O(delta)."""
        from janusgraph_tpu.observability import registry

        with self._lock:
            info = {"path": "cold"}
            if self.csr is not None:
                got = overlay_since(self.graph, self.epoch)
                if got is None:
                    registry.counter("olap.delta.capture_overflow").inc()
                    self.csr = None  # fall through to the full load
                else:
                    overlay, upto = got
                    registry.set_gauge(
                        "olap.delta.overlay_depth", float(overlay.size)
                    )
                    if overlay.size == 0:
                        info = {"path": "warm", "overlay": 0}
                        return self.csr, None, info
                    view = OverlayView(
                        self.csr, overlay,
                        max_lane_cells=self.max_lane_cells,
                    )
                    view.upto_epoch = upto
                    if overlay.size > self.max_overlay:
                        # too deep to consume fused: fold into the base
                        # (still zero store reads)
                        self._compact(view)
                        info = {
                            "path": "refresh",
                            "overlay": overlay.size,
                        }
                        return self.csr, None, info
                    info = {"path": "fused", "overlay": overlay.size}
                    return self.csr, view, info
            from janusgraph_tpu.olap.csr import load_csr_snapshot

            # graphlint: disable=JG403 -- single-repacker by design: acquire() holds _lock across the cold repack so concurrent submitters share ONE snapshot load instead of racing N repacks
            csr, epoch = load_csr_snapshot(self.graph)
            self._install(csr, epoch)
            registry.counter("olap.delta.packs").inc()
            registry.set_gauge("olap.delta.overlay_depth", 0.0)
            return self.csr, None, {"path": "cold"}

    def adopt(self, csr, epoch: int) -> None:
        """Install an externally materialized base (submit()'s
        materialize branch, or a fleet warm-up pack — server/fleet.py)
        so the next acquire resumes from it."""
        with self._lock:
            self._install(csr, epoch)

    def _install(self, csr, epoch: int) -> None:
        """Replace the base pack (lock held): generation bump invalidates
        every cached executor — their device packs cover the OLD base."""
        self.csr = csr
        self.epoch = epoch
        self.generation += 1
        # graphlint: disable=JG401 -- every caller (acquire, adopt) holds self._lock per this method's contract ("lock held"); the analyzer cannot see caller-held locks
        self._executors.clear()

    # ------------------------------------------------- warm executor cache
    def cached_executor(self, key: Tuple):
        """A previously stored executor for this base-pack generation, or
        None. Keys carry the executor kind + constructor signature; the
        overlay is NOT part of the key — callers swap it per submit via
        ``set_delta`` (compiled executables stay sig-keyed inside)."""
        from janusgraph_tpu.observability import registry

        with self._lock:
            ex = self._executors.get(key)
        if ex is not None:
            registry.counter("olap.executor.cache_hits").inc()
        return ex

    def store_executor(self, key: Tuple, ex, csr) -> None:
        """Cache one freshly built executor IF it was built over the
        CURRENT base pack (a concurrent compaction between acquire and
        build means the executor's device arrays are already stale —
        dropping it is the cheap correct answer)."""
        from janusgraph_tpu.observability import registry

        registry.counter("olap.executor.cache_misses").inc()
        with self._lock:
            if csr is self.csr:
                self._executors[key] = ex

    # ----------------------------------------------------------- compaction
    def _threshold(self) -> int:
        if self.compact_threshold:
            return self.compact_threshold
        if self._decision is None:
            from janusgraph_tpu.olap import autotune

            try:
                import jax

                kind = getattr(
                    jax.devices()[0], "device_kind", "cpu"
                )
            except Exception:  # noqa: BLE001 - jax may be unavailable
                kind = "cpu"
            self._decision = autotune.decide_delta(
                num_edges=self.csr.num_edges if self.csr is not None else 0,
                num_vertices=(
                    self.csr.num_vertices if self.csr is not None else 0
                ),
                device_kind=kind,
            )
        return self._decision.compact_threshold

    def maybe_compact(self) -> bool:
        """Fold the pending overlay into the base pack when it crosses
        the (autotuner-decided) threshold. Off the superstep path —
        submit() calls this AFTER the run returns."""
        with self._lock:
            if self.csr is None:
                return False
            got = overlay_since(self.graph, self.epoch)
            if got is None or got[0].size == 0:
                return False
            overlay, upto = got
            if overlay.size < self._threshold():
                return False
            view = OverlayView(
                self.csr, overlay, max_lane_cells=self.max_lane_cells
            )
            view.upto_epoch = upto
            self._compact(view)
            return True

    def _compact(self, view: OverlayView) -> None:
        """Materialize base+overlay into a fresh base pack (zero store
        reads), advance the epoch, persist with tmp+rename when
        configured. Call under the lock."""
        import time as _time

        from janusgraph_tpu.observability import flight_recorder, registry

        t0 = _time.perf_counter()
        depth = view.depth
        # anchor at the max epoch actually folded — records committed
        # mid-materialize stay pending instead of being lost
        self._install(
            materialize(
                self.csr, view.overlay,
                idm=getattr(self.graph, "idm", None),
            ),
            getattr(view, "upto_epoch", self.epoch),
        )
        wall_ms = (_time.perf_counter() - t0) * 1000.0
        registry.counter("olap.delta.compactions").inc()
        registry.set_gauge("olap.delta.overlay_depth", 0.0)
        flight_recorder.record(
            "delta_compact", depth=depth,
            edges=self.csr.num_edges, vertices=self.csr.num_vertices,
            wall_ms=round(wall_ms, 3), threshold=self._threshold(),
        )
        if self.snapshot_path:
            try:
                save_snapshot(self.snapshot_path, self.csr, self.epoch)
            except OSError:
                pass  # persistence is best-effort, the pack is in memory


def get_snapshot(graph) -> Optional[DeltaSnapshot]:
    """The graph's lazily created DeltaSnapshot (None when the delta
    machinery is disabled or the graph has no change capture)."""
    if getattr(graph, "change_capture", None) is None:
        return None
    snap = getattr(graph, "_delta_snapshot", None)
    if snap is None:
        snap = DeltaSnapshot(graph)
        graph._delta_snapshot = snap
    return snap


# ---------------------------------------------------------------------------
# Snapshot persistence (tmp+rename, same discipline as checkpoints)
# ---------------------------------------------------------------------------

def save_snapshot(path: str, csr, epoch: int) -> None:
    import os
    import tempfile

    arrays = {
        "vertex_ids": csr.vertex_ids,
        "out_indptr": csr.out_indptr,
        "out_dst": csr.out_dst,
        "in_indptr": csr.in_indptr,
        "in_src": csr.in_src,
        "out_degree": csr.out_degree,
        "epoch": np.asarray(epoch, dtype=np.int64),
    }
    if csr.labels is not None:
        arrays["labels"] = csr.labels
    if csr.out_edge_type is not None:
        arrays["out_edge_type"] = csr.out_edge_type
        arrays["in_edge_type"] = csr.in_edge_type
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str):
    """(CSRGraph, epoch) or None. The epoch only binds to the writing
    process's backend instance — a reloaded snapshot in a fresh process
    is a warm PACK, not a warm epoch, so callers must re-anchor it."""
    import os

    from janusgraph_tpu.olap.csr import CSRGraph

    if not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        csr = CSRGraph(
            vertex_ids=z["vertex_ids"],
            out_indptr=z["out_indptr"],
            out_dst=z["out_dst"],
            in_indptr=z["in_indptr"],
            in_src=z["in_src"],
            out_degree=z["out_degree"],
            labels=z["labels"] if "labels" in z else None,
            in_edge_type=(
                z["in_edge_type"] if "in_edge_type" in z else None
            ),
            out_edge_type=(
                z["out_edge_type"] if "out_edge_type" in z else None
            ),
        )
        return csr, int(z["epoch"])
    except Exception:  # noqa: BLE001 - torn/garbage file = cold start
        return None


class ResultView:
    """Minimal CSRGraph-shaped mapping for fused-run results: surviving
    vertex ids aligned row-for-row with the compacted state arrays
    (value()/by_vertex()/write_back read exactly these fields)."""

    def __init__(self, vertex_ids: np.ndarray):
        self.vertex_ids = np.asarray(vertex_ids, dtype=np.int64)
        self._order = np.argsort(self.vertex_ids, kind="stable")
        self._sorted = self.vertex_ids[self._order]

    @property
    def num_vertices(self) -> int:
        return len(self.vertex_ids)

    @property
    def local_num_vertices(self) -> int:
        return len(self.vertex_ids)

    def index_of(self, vid: int) -> int:
        i = int(np.searchsorted(self._sorted, vid))
        if i >= len(self._sorted) or self._sorted[i] != vid:
            raise KeyError(f"vertex id {vid} not in snapshot")
        return int(self._order[i])

    def id_of(self, index: int) -> int:
        return int(self.vertex_ids[index])


def compact_result(view: OverlayView, states: Dict[str, np.ndarray]):
    """(states filtered to surviving rows, ResultView): drops removed
    base slots from a fused run's output so results cover exactly the
    live vertex set (what a repacked run would have returned)."""
    _outd, _ind, active = view.fused_degrees()
    mask = active[: view.n_real] > 0
    filtered = {k: np.asarray(v)[mask] for k, v in states.items()}
    return filtered, ResultView(view.vertex_ids[mask])


def program_delta_compatible(program) -> bool:
    """Whether a vertex program can consume the overlay FUSED: default
    edge view only (typed channels aggregate over their own packs, which
    the lanes do not patch), no sddmm (row-dst vectors are base-layout)."""
    from janusgraph_tpu.olap.vertex_program import VertexProgram

    if getattr(program, "message_mode", None) == "sddmm":
        return False
    if getattr(program, "edge_channels", None):
        return False
    if type(program).channel_for is not VertexProgram.channel_for:
        return False
    return True
