"""Sharded checkpoint format: per-shard state slices + an atomic manifest.

The single-file checkpoint (olap/checkpoint.py) serializes the FULL vertex
state each interval — fine on one chip, but on a mesh it funnels every
shard's state through one writer and one rename, and a torn write loses the
whole interval for the whole mesh. This module is the multi-chip form:

- each shard's rows land in their own ``shard-<s>.npz`` slice, digest-
  embedded and written atomically (tmp + rename, previous slice demoted to
  ``.prev``) — slices can be written independently and, on a real multi-
  controller deployment, by different hosts;
- a checkpoint COMMITS only when ``manifest.json`` lands (tmp + rename,
  previous manifest demoted to ``.prev``). The manifest names every slice
  by content digest, carries the reduced aggregators + step counter, and
  embeds its own digest. The manifest rename is the linearization point:
  the superstep boundary it records is the cross-shard CONSISTENCY CUT the
  BSP barrier already guarantees (no shard can be "between" supersteps at
  a barrier), so rolling every shard back to the last manifest and
  replaying reproduces the exact run.

Torn-write containment, per file class:

- torn SLICE write: the slice's digest won't match the manifest; the
  loader falls back to the slice's ``.prev`` twin IF its digest matches
  (the tear happened after demotion but before promotion), else the whole
  manifest is incomplete and the loader falls back to ``manifest.json
  .prev`` — one interval lost, never the run;
- torn MANIFEST write: the JSON parse or embedded digest fails; the
  loader falls back to ``manifest.json.prev`` whose slices are still on
  disk (every slice save keeps its ``.prev`` twin precisely so the
  previous manifest stays loadable).

Slices store REAL (unpadded) rows keyed by contiguous row ranges, so a
checkpoint written by an S-shard mesh restores on any shard count — the
executors re-derive padding rows from a fresh ``setup()`` exactly like the
single-file resume path.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.checkpoint import _content_digest

MANIFEST_NAME = "manifest.json"
_MANIFEST_VERSION = 1
_STATE = "state__"


def shard_ranges(num_rows: int, num_shards: int) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) row ranges, one per shard (ceil split — the
    same contiguous-block convention as ShardedCSR / host_partition_range)."""
    S = max(1, int(num_shards))
    size = -(-max(num_rows, 1) // S)
    return [
        (min(s * size, num_rows), min((s + 1) * size, num_rows))
        for s in range(S)
    ]


def _slice_path(dir_path: str, shard: int) -> str:
    return os.path.join(dir_path, f"shard-{shard}.npz")


def _atomic_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """tmp + rename in the target directory; the previous file survives as
    ``<path>.prev`` (same two-rename discipline as olap/checkpoint.py)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _manifest_digest(body: dict) -> str:
    """Digest over the canonical JSON of the manifest body (sorted keys,
    ``digest`` field excluded) — a torn/edited manifest cannot verify."""
    canon = json.dumps(
        {k: v for k, v in sorted(body.items()) if k != "digest"},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()


def save_sharded_checkpoint(
    dir_path: str,
    state: Dict[str, np.ndarray],
    memory: Dict[str, object],
    steps_done: int,
    num_shards: int,
) -> None:
    """Write per-shard slices, then commit the manifest. ``state`` holds
    the REAL rows (padding stripped); each array's leading dim is the
    vertex axis and is sliced into ``num_shards`` contiguous blocks."""
    state = {k: np.asarray(v) for k, v in state.items()}
    num_rows = int(next(iter(state.values())).shape[0]) if state else 0
    ranges = shard_ranges(num_rows, num_shards)
    shards = []
    for s, (lo, hi) in enumerate(ranges):
        arrays = {
            _STATE + k: np.ascontiguousarray(v[lo:hi])
            for k, v in state.items()
        }
        digest = _content_digest(arrays)
        arrays["meta__digest"] = digest
        _atomic_npz(_slice_path(dir_path, s), arrays)
        shards.append({
            "file": f"shard-{s}.npz",
            "rows": [int(lo), int(hi)],
            "digest": digest.tobytes().hex(),
        })
    body = {
        "version": _MANIFEST_VERSION,
        "steps": int(steps_done),
        "num_shards": int(num_shards),
        "num_rows": num_rows,
        "state_keys": sorted(state),
        "memory": {k: float(v) for k, v in memory.items()},
        "shards": shards,
    }
    body["digest"] = _manifest_digest(body)
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(body, f)
        if os.path.exists(mpath):
            os.replace(mpath, mpath + ".prev")
        os.replace(tmp, mpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.record(
        "checkpoint", action="shard_save", steps=int(steps_done),
        shards=int(num_shards),
    )


def _read_manifest(mpath: str) -> Optional[dict]:
    """One manifest file, digest-verified; None when missing/torn/edited."""
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            body = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(body, dict) or body.get("version") != _MANIFEST_VERSION:
        return None
    if body.get("digest") != _manifest_digest(body):
        return None
    return body


def _read_slice(
    path: str, want_digest: str
) -> Optional[Dict[str, np.ndarray]]:
    """One slice file IF its content digest matches the manifest's record.
    Missing/torn/mismatched files return None (caller tries ``.prev``)."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception:  # zipfile/format errors: torn or truncated
        return None
    arrays.pop("meta__digest", None)
    if _content_digest(arrays).tobytes().hex() != want_digest:
        return None
    return {
        k[len(_STATE):]: v for k, v in arrays.items() if k.startswith(_STATE)
    }


def _assemble(dir_path: str, body: dict, record_fallbacks: bool = True) -> Optional[
    Tuple[Dict[str, np.ndarray], Dict[str, float], int]
]:
    """Collect every slice the manifest names — current file first, its
    ``.prev`` twin second (content-addressed by digest, so whichever file
    carries the manifest's bytes is the right one). None if any shard has
    neither."""
    from janusgraph_tpu.observability import flight_recorder, registry

    num_rows = int(body["num_rows"])
    keys = list(body["state_keys"])
    pieces: List[Dict[str, np.ndarray]] = []
    for rec in body["shards"]:
        path = os.path.join(dir_path, rec["file"])
        sl = _read_slice(path, rec["digest"])
        if sl is None:
            sl = _read_slice(path + ".prev", rec["digest"])
            if sl is not None and record_fallbacks:
                # a demoted twin carried the manifest's bytes: the current
                # slice write was torn after demotion
                registry.counter("olap.checkpoint.shard_fallback").inc()
                flight_recorder.record(
                    "checkpoint", action="shard_fallback",
                    file=rec["file"], steps=int(body["steps"]),
                )
        if sl is None or set(sl) != set(keys):
            return None
        pieces.append(sl)
    state = {
        k: np.concatenate([p[k] for p in pieces], axis=0)[:num_rows]
        for k in keys
    }
    return state, dict(body.get("memory", {})), int(body["steps"])


def load_sharded_checkpoint(
    dir_path: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, float], int]]:
    """(state, memory, steps_done) from the newest COMPLETE checkpoint:
    the current manifest if every slice verifies, else ``manifest.json
    .prev`` — a torn write (slice or manifest) costs one interval, never
    the run. None when no complete checkpoint exists."""
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    current = _read_manifest(mpath)
    if current is not None:
        out = _assemble(dir_path, current)
        if out is not None:
            return out
    fallback = _read_manifest(mpath + ".prev")
    if fallback is None:
        return None
    # the previous manifest's slices usually live in the .prev twins (the
    # newer save demoted them) — that is the expected layout, not a
    # per-shard incident, so slice fallbacks are not re-counted here
    out = _assemble(dir_path, fallback, record_fallbacks=False)
    if out is not None and os.path.exists(mpath):
        from janusgraph_tpu.observability import flight_recorder, registry

        registry.counter("olap.checkpoint.manifest_fallback").inc()
        # the newest manifest (or one of its slices) was torn and .prev
        # saved the run — the exact event a post-mortem timeline needs
        flight_recorder.record(
            "checkpoint", action="manifest_fallback", steps=int(out[2]),
        )
    return out
