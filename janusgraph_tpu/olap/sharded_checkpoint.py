"""Sharded checkpoint format: per-shard state slices + an atomic manifest.

The single-file checkpoint (olap/checkpoint.py) serializes the FULL vertex
state each interval — fine on one chip, but on a mesh it funnels every
shard's state through one writer and one rename, and a torn write loses the
whole interval for the whole mesh. This module is the multi-chip form:

- each shard's rows land in their own ``shard-<s>.npz`` slice, digest-
  embedded and written atomically (tmp + rename, previous slice demoted to
  ``.prev``) — slices can be written independently and, on a real multi-
  controller deployment, by different hosts;
- a checkpoint COMMITS only when ``manifest.json`` lands (tmp + rename,
  previous manifest demoted to ``.prev``). The manifest names every slice
  by content digest, carries the reduced aggregators + step counter, and
  embeds its own digest. The manifest rename is the linearization point:
  the superstep boundary it records is the cross-shard CONSISTENCY CUT the
  BSP barrier already guarantees (no shard can be "between" supersteps at
  a barrier), so rolling every shard back to the last manifest and
  replaying reproduces the exact run.

Torn-write containment, per file class:

- torn SLICE write: the slice's digest won't match the manifest; the
  loader falls back to the slice's ``.prev`` twin IF its digest matches
  (the tear happened after demotion but before promotion), else the whole
  manifest is incomplete and the loader falls back to ``manifest.json
  .prev`` — one interval lost, never the run;
- torn MANIFEST write: the JSON parse or embedded digest fails; the
  loader falls back to ``manifest.json.prev`` whose slices are still on
  disk (every slice save keeps its ``.prev`` twin precisely so the
  previous manifest stays loadable).

Slices store REAL (unpadded) rows keyed by contiguous row ranges, so a
checkpoint written by an S-shard mesh restores on any shard count — the
executors re-derive padding rows from a fresh ``setup()`` exactly like the
single-file resume path.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.checkpoint import _content_digest

MANIFEST_NAME = "manifest.json"
_MANIFEST_VERSION = 1
_STATE = "state__"


def shard_ranges(num_rows: int, num_shards: int) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) row ranges, one per shard (ceil split — the
    same contiguous-block convention as ShardedCSR / host_partition_range)."""
    S = max(1, int(num_shards))
    size = -(-max(num_rows, 1) // S)
    return [
        (min(s * size, num_rows), min((s + 1) * size, num_rows))
        for s in range(S)
    ]


def _slice_path(dir_path: str, shard: int) -> str:
    return os.path.join(dir_path, f"shard-{shard}.npz")


def _atomic_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """tmp + rename in the target directory; the previous file survives as
    ``<path>.prev`` (same two-rename discipline as olap/checkpoint.py)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _manifest_digest(body: dict) -> str:
    """Digest over the canonical JSON of the manifest body (sorted keys,
    ``digest`` field excluded) — a torn/edited manifest cannot verify."""
    canon = json.dumps(
        {k: v for k, v in sorted(body.items()) if k != "digest"},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()


def save_sharded_checkpoint(
    dir_path: str,
    state: Dict[str, np.ndarray],
    memory: Dict[str, object],
    steps_done: int,
    num_shards: int,
) -> None:
    """Write per-shard slices, then commit the manifest. ``state`` holds
    the REAL rows (padding stripped); each array's leading dim is the
    vertex axis and is sliced into ``num_shards`` contiguous blocks."""
    state = {k: np.asarray(v) for k, v in state.items()}
    num_rows = int(next(iter(state.values())).shape[0]) if state else 0
    ranges = shard_ranges(num_rows, num_shards)
    shards = []
    for s, (lo, hi) in enumerate(ranges):
        arrays = {
            _STATE + k: np.ascontiguousarray(v[lo:hi])
            for k, v in state.items()
        }
        digest = _content_digest(arrays)
        arrays["meta__digest"] = digest
        _atomic_npz(_slice_path(dir_path, s), arrays)
        shards.append({
            "file": f"shard-{s}.npz",
            "rows": [int(lo), int(hi)],
            "digest": digest.tobytes().hex(),
        })
    body = {
        "version": _MANIFEST_VERSION,
        "steps": int(steps_done),
        "num_shards": int(num_shards),
        "num_rows": num_rows,
        "state_keys": sorted(state),
        "memory": {k: float(v) for k, v in memory.items()},
        "shards": shards,
    }
    body["digest"] = _manifest_digest(body)
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(body, f)
        if os.path.exists(mpath):
            os.replace(mpath, mpath + ".prev")
        os.replace(tmp, mpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.record(
        "checkpoint", action="shard_save", steps=int(steps_done),
        shards=int(num_shards),
    )


def _read_manifest(mpath: str) -> Optional[dict]:
    """One manifest file, digest-verified; None when missing/torn/edited."""
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            body = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(body, dict) or body.get("version") != _MANIFEST_VERSION:
        return None
    if body.get("digest") != _manifest_digest(body):
        return None
    return body


def _read_slice(
    path: str, want_digest: str
) -> Optional[Dict[str, np.ndarray]]:
    """One slice file IF its content digest matches the manifest's record.
    Missing/torn/mismatched files return None (caller tries ``.prev``)."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception:  # zipfile/format errors: torn or truncated
        return None
    arrays.pop("meta__digest", None)
    if _content_digest(arrays).tobytes().hex() != want_digest:
        return None
    return {
        k[len(_STATE):]: v for k, v in arrays.items() if k.startswith(_STATE)
    }


def _assemble(dir_path: str, body: dict, record_fallbacks: bool = True) -> Optional[
    Tuple[Dict[str, np.ndarray], Dict[str, float], int]
]:
    """Collect every slice the manifest names — current file first, its
    ``.prev`` twin second (content-addressed by digest, so whichever file
    carries the manifest's bytes is the right one). None if any shard has
    neither."""
    from janusgraph_tpu.observability import flight_recorder, registry

    num_rows = int(body["num_rows"])
    keys = list(body["state_keys"])
    pieces: List[Dict[str, np.ndarray]] = []
    for rec in body["shards"]:
        path = os.path.join(dir_path, rec["file"])
        sl = _read_slice(path, rec["digest"])
        if sl is None:
            sl = _read_slice(path + ".prev", rec["digest"])
            if sl is not None and record_fallbacks:
                # a demoted twin carried the manifest's bytes: the current
                # slice write was torn after demotion
                registry.counter("olap.checkpoint.shard_fallback").inc()
                flight_recorder.record(
                    "checkpoint", action="shard_fallback",
                    file=rec["file"], steps=int(body["steps"]),
                )
        if sl is None or set(sl) != set(keys):
            return None
        pieces.append(sl)
    state = {
        k: np.concatenate([p[k] for p in pieces], axis=0)[:num_rows]
        for k in keys
    }
    return state, dict(body.get("memory", {})), int(body["steps"])


# ---------------------------------------------------------------------------
# CSR snapshot checkpoints (fleet replica warm-up)
# ---------------------------------------------------------------------------
#
# The per-shard-slice + digest-verified-manifest discipline above also
# carries the snapshot-CSR cache across processes: a serving replica
# exports its base pack once, and a JOINING replica hydrates from the
# files instead of re-scanning storage (zero edgestore reads — the
# warm-up half of server/fleet.py). Unlike the state checkpoints, a CSR
# pack mixes vertex-axis and edge-axis arrays, so slices are row-range
# shards whose edge arrays cover exactly the rows' indptr spans — the
# same contiguous-block convention as ShardedCSR, and reassembly is
# byte-identical to the exported arrays (the acceptance contract).

_CSR_KIND = "csr-snapshot"

#: arrays present only when the exported pack carries them (the loader
#: passes absent ones as None, matching a scanned snapshot)
_CSR_OPTIONAL = ("labels", "out_edge_type", "in_edge_type")


def save_csr_checkpoint(
    dir_path: str, csr, epoch: int, num_shards: int = 1
) -> None:
    """Export one CSR snapshot pack as a sharded checkpoint: per-shard
    row-range slices (vertex arrays by rows, edge arrays by the rows'
    indptr spans), each digest-embedded and written atomically, committed
    by the digest-verified manifest."""
    n = int(len(csr.vertex_ids))
    ranges = shard_ranges(n, num_shards)
    shards = []
    for s, (lo, hi) in enumerate(ranges):
        olo, ohi = int(csr.out_indptr[lo]), int(csr.out_indptr[hi])
        ilo, ihi = int(csr.in_indptr[lo]), int(csr.in_indptr[hi])
        arrays = {
            "vertex_ids": np.ascontiguousarray(csr.vertex_ids[lo:hi]),
            "out_degree": np.ascontiguousarray(csr.out_degree[lo:hi]),
            "out_indptr": np.ascontiguousarray(
                csr.out_indptr[lo: hi + 1]
            ),
            "in_indptr": np.ascontiguousarray(csr.in_indptr[lo: hi + 1]),
            "out_dst": np.ascontiguousarray(csr.out_dst[olo:ohi]),
            "in_src": np.ascontiguousarray(csr.in_src[ilo:ihi]),
        }
        if csr.labels is not None:
            arrays["labels"] = np.ascontiguousarray(csr.labels[lo:hi])
        if csr.out_edge_type is not None:
            arrays["out_edge_type"] = np.ascontiguousarray(
                csr.out_edge_type[olo:ohi]
            )
            arrays["in_edge_type"] = np.ascontiguousarray(
                csr.in_edge_type[ilo:ihi]
            )
        digest = _content_digest(arrays)
        arrays["meta__digest"] = digest
        _atomic_npz(_slice_path(dir_path, s), arrays)
        shards.append({
            "file": f"shard-{s}.npz",
            "rows": [int(lo), int(hi)],
            "digest": digest.tobytes().hex(),
        })
    body = {
        "version": _MANIFEST_VERSION,
        "kind": _CSR_KIND,
        "epoch": int(epoch),
        "num_shards": int(num_shards),
        "num_rows": n,
        "num_edges": int(csr.num_edges),
        "optional": sorted(
            k for k in _CSR_OPTIONAL
            if getattr(csr, k, None) is not None
        ),
        "shards": shards,
    }
    body["digest"] = _manifest_digest(body)
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(body, f)
        if os.path.exists(mpath):
            os.replace(mpath, mpath + ".prev")
        os.replace(tmp, mpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.record(
        "checkpoint", action="csr_save", rows=n,
        edges=int(csr.num_edges), shards=int(num_shards),
    )


def _assemble_csr(dir_path: str, body: dict):
    pieces = []
    for rec in body["shards"]:
        path = os.path.join(dir_path, rec["file"])
        sl = None
        for candidate in (path, path + ".prev"):
            if not os.path.exists(candidate):
                continue
            try:
                with np.load(candidate) as z:
                    arrays = {k: z[k] for k in z.files}
            except Exception:  # noqa: BLE001 - torn/truncated slice
                continue
            arrays.pop("meta__digest", None)
            if _content_digest(arrays).tobytes().hex() == rec["digest"]:
                sl = arrays
                break
        if sl is None:
            return None
        pieces.append(sl)
    if not pieces:
        return None

    def _cat(key, indptr=False):
        if key not in pieces[0]:
            return None
        if indptr:
            # each slice stored indptr[lo:hi+1] with ABSOLUTE values;
            # drop the duplicated boundary of every later slice
            parts = [pieces[0][key]] + [p[key][1:] for p in pieces[1:]]
        else:
            parts = [p[key] for p in pieces]
        return np.concatenate(parts) if len(parts) > 1 else parts[0]

    from janusgraph_tpu.olap.csr import CSRGraph

    csr = CSRGraph(
        vertex_ids=_cat("vertex_ids"),
        out_indptr=_cat("out_indptr", indptr=True),
        out_dst=_cat("out_dst"),
        in_indptr=_cat("in_indptr", indptr=True),
        in_src=_cat("in_src"),
        out_degree=_cat("out_degree"),
        labels=_cat("labels"),
        out_edge_type=_cat("out_edge_type"),
        in_edge_type=_cat("in_edge_type"),
    )
    if len(csr.vertex_ids) != int(body["num_rows"]) or (
        len(csr.out_dst) != int(body["num_edges"])
    ):
        return None
    return csr, int(body["epoch"])


def load_csr_checkpoint(dir_path: str):
    """(CSRGraph, epoch) from the newest COMPLETE CSR snapshot checkpoint
    (current manifest first, ``manifest.json.prev`` fallback — the state
    checkpoints' torn-write containment), or None. Arrays reassemble
    byte-identical to the exported pack; the epoch binds to the EXPORTING
    process's backend — a joining replica re-anchors at its own observed
    epoch (server/fleet.py warm_replica)."""
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    for candidate in (mpath, mpath + ".prev"):
        body = _read_manifest(candidate)
        if body is None or body.get("kind") != _CSR_KIND:
            continue
        out = _assemble_csr(dir_path, body)
        if out is not None:
            if candidate != mpath:
                from janusgraph_tpu.observability import (
                    flight_recorder,
                    registry,
                )

                registry.counter(
                    "olap.checkpoint.manifest_fallback"
                ).inc()
                flight_recorder.record(
                    "checkpoint", action="manifest_fallback",
                    steps=int(out[1]),
                )
            return out
    return None


def load_sharded_checkpoint(
    dir_path: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, float], int]]:
    """(state, memory, steps_done) from the newest COMPLETE checkpoint:
    the current manifest if every slice verifies, else ``manifest.json
    .prev`` — a torn write (slice or manifest) costs one interval, never
    the run. None when no complete checkpoint exists."""
    mpath = os.path.join(dir_path, MANIFEST_NAME)
    current = _read_manifest(mpath)
    if current is not None:
        out = _assemble(dir_path, current)
        if out is not None:
            return out
    fallback = _read_manifest(mpath + ".prev")
    if fallback is None:
        return None
    # the previous manifest's slices usually live in the .prev twins (the
    # newer save demoted them) — that is the expected layout, not a
    # per-shard incident, so slice fallbacks are not re-counted here
    out = _assemble(dir_path, fallback, record_fallbacks=False)
    if out is not None and os.path.exists(mpath):
        from janusgraph_tpu.observability import flight_recorder, registry

        registry.counter("olap.checkpoint.manifest_fallback").inc()
        # the newest manifest (or one of its slices) was torn and .prev
        # saved the run — the exact event a post-mortem timeline needs
        flight_recorder.record(
            "checkpoint", action="manifest_fallback", steps=int(out[2]),
        )
    return out
