"""Command-line entry points.

Capability parity with the reference's dist scripts
(reference: janusgraph-dist/src/assembly/static/bin/janusgraph-server.sh —
start the server from a config file; gremlin.sh — interactive console;
janusgraph.sh — combined lifecycle):

  python -m janusgraph_tpu server  --config graph.json [--port 8182] [--auth]
  python -m janusgraph_tpu console [--config graph.json | --remote host:port]
  python -m janusgraph_tpu bench   [--scale N]
"""

from __future__ import annotations

import argparse
import code
import json
import sys
from typing import Optional


def _load_config(path: Optional[str]) -> dict:
    if not path:
        return {"storage.backend": "inmemory", "ids.authority-wait-ms": 0.0}
    with open(path) as f:
        return json.load(f)


def cmd_server(args) -> int:
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    cfg = _load_config(args.config)
    graph = open_graph(cfg)
    if args.load_gods:
        from janusgraph_tpu.core import gods

        gods.load(graph)
    manager = JanusGraphManager.get_instance()
    manager.put_graph(args.graph_name, graph)

    authenticator = None
    if args.auth_credentials:
        from janusgraph_tpu.core.graph import open_graph as _og
        from janusgraph_tpu.server import (
            CredentialsAuthenticator,
            HMACAuthenticator,
        )

        creds_graph = _og(_load_config(args.auth_credentials))
        authenticator = HMACAuthenticator(CredentialsAuthenticator(creds_graph))

    server = JanusGraphServer(
        manager=manager,
        default_graph=args.graph_name,
        authenticator=authenticator,
        host=args.host,
        port=args.port,
    ).start()
    print(f"JanusGraph-TPU server listening on {args.host}:{server.port}")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        graph.close()
    return 0


def cmd_console(args) -> int:
    banner = "JanusGraph-TPU console — `g` is the traversal source, `P` the predicates"
    ns = {}
    if args.remote:
        from janusgraph_tpu.driver import JanusGraphClient

        host, _, port = args.remote.partition(":")
        client = JanusGraphClient(host=host, port=int(port or 8182))
        ns["client"] = client
        ns["submit"] = client.submit
        banner = (
            "JanusGraph-TPU remote console — submit('g.V()...') runs on "
            f"{args.remote}"
        )
    else:
        from janusgraph_tpu.core.graph import open_graph
        from janusgraph_tpu.core.traversal import P

        graph = open_graph(_load_config(args.config))
        if args.load_gods:
            from janusgraph_tpu.core import gods

            gods.load(graph)
        ns.update({"graph": graph, "g": graph.traversal(), "P": P})
    code.interact(banner=banner, local=ns)
    return 0


def cmd_bench(args) -> int:
    import os

    if args.scale:
        os.environ["BENCH_SCALE"] = str(args.scale)
    root = os.path.join(os.path.dirname(__file__), "..")
    sys.path.insert(0, root)
    import bench

    bench.main()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="janusgraph_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    ps = sub.add_parser("server", help="start the query server")
    ps.add_argument("--config", help="graph config JSON file")
    ps.add_argument("--graph-name", default="graph")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=8182)
    ps.add_argument("--auth-credentials", help="credentials-graph config JSON")
    ps.add_argument("--load-gods", action="store_true",
                    help="preload the Graph of the Gods example")
    ps.set_defaults(fn=cmd_server)

    pc = sub.add_parser("console", help="interactive console")
    pc.add_argument("--config", help="graph config JSON file")
    pc.add_argument("--remote", help="host:port of a running server")
    pc.add_argument("--load-gods", action="store_true")
    pc.set_defaults(fn=cmd_console)

    pb = sub.add_parser("bench", help="run the benchmark")
    pb.add_argument("--scale", type=int)
    pb.set_defaults(fn=cmd_bench)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
