"""Command-line entry points.

Capability parity with the reference's dist scripts
(reference: janusgraph-dist/src/assembly/static/bin/janusgraph-server.sh —
start the server from a config file; gremlin.sh — interactive console;
janusgraph.sh — combined lifecycle):

  python -m janusgraph_tpu server  --config graph.json [--port 8182] [--auth]
  python -m janusgraph_tpu console [--config graph.json | --remote host:port]
  python -m janusgraph_tpu bench   [--scale N]
"""

from __future__ import annotations

import argparse
import code
import json
import os
import sys
from typing import Optional


def _load_config(path: Optional[str]) -> dict:
    if not path:
        return {"storage.backend": "inmemory", "ids.authority-wait-ms": 0.0}
    with open(path) as f:
        return json.load(f)


def cmd_server(args) -> int:
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.observability import set_replica
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    cfg = _load_config(args.config)
    graph = open_graph(cfg)
    replica = args.replica_name or graph.config.get(
        "server.fleet.replica-name"
    )
    if replica:
        # tag this process's flight events / logs / metrics with the
        # fleet identity (observability/identity.py)
        set_replica(replica)
    if args.load_gods:
        from janusgraph_tpu.core import gods

        gods.load(graph)
    manager = JanusGraphManager.get_instance()
    manager.put_graph(args.graph_name, graph)

    authenticator = None
    if args.auth_credentials:
        from janusgraph_tpu.core.graph import open_graph as _og
        from janusgraph_tpu.server import (
            CredentialsAuthenticator,
            HMACAuthenticator,
        )

        creds_cfg = _load_config(args.auth_credentials)
        # server.auth.credentials-db names the credentials graph
        # (reference: the credentials-graph convention)
        creds_cfg.setdefault(
            "graph.graphname",
            graph.config.get("server.auth.credentials-db"),
        )
        creds_graph = _og(creds_cfg)
        secret = graph.config.get("server.auth.secret")
        authenticator = HMACAuthenticator(
            CredentialsAuthenticator(creds_graph),
            secret=secret.encode() if secret else None,
            token_ttl_seconds=(
                graph.config.get("server.auth.token-ttl-ms") / 1000.0
            ),
        )

    admission = None
    if graph.config.get("server.admission.enabled"):
        from janusgraph_tpu.server.admission import AdmissionController

        admission = AdmissionController.from_config(graph.config)
    server = JanusGraphServer(
        manager=manager,
        default_graph=args.graph_name,
        authenticator=authenticator,
        host=args.host,
        port=args.port,
        max_request_bytes=graph.config.get("server.max-request-bytes"),
        max_query_length=graph.config.get("server.max-query-length"),
        request_timeout_s=graph.config.get("server.request-timeout-s"),
        auto_commit=graph.config.get("server.auto-commit"),
        admission=admission,
        admission_enabled=graph.config.get("server.admission.enabled"),
        default_deadline_ms=graph.config.get("server.deadline.default-ms"),
        max_deadline_ms=graph.config.get("server.deadline.max-ms"),
        history_enabled=graph.config.get("metrics.history-enabled"),
        slo_enabled=graph.config.get("metrics.slo-enabled"),
        slo_specs=_slo_specs_from_config(graph.config),
        replica_name=replica,
        profiler_enabled=graph.config.get("metrics.profile-enabled"),
        watchdog_enabled=graph.config.get("server.watchdog-enabled"),
        bundle_dir=graph.config.get("metrics.bundle-dir"),
    ).start()
    print(f"JanusGraph-TPU server listening on {args.host}:{server.port}")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        graph.close()
    return 0


def cmd_fleet(args) -> int:
    """Run a serving FLEET: N JanusGraphServer replicas over ONE shared
    storage backend, fronted by the consistent-hash/least-loaded router
    (server/fleet.py) with health probes, state gossip, and replica
    warm-up from the shard-checkpoint snapshot pack. The in-process shape
    of the reference deployment model — for production the same router
    library fronts replicas on separate hosts speaking to a shared
    storage-server endpoint (storage.backend=remote)."""
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.observability import set_replica
    from janusgraph_tpu.server import (
        FleetFrontend,
        FleetRouter,
        JanusGraphManager,
        JanusGraphServer,
        StateGossip,
    )
    from janusgraph_tpu.server.fleet import warm_replica

    cfg = _load_config(args.config)
    set_replica("fleet-frontend")
    # one shared backing for every replica: inmemory shares the manager
    # object in-process; remote/local replicas each open their own client
    # to the SAME endpoint/directory (the config already names it)
    shared = None
    if cfg.get("storage.backend", "inmemory") == "inmemory":
        from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

        shared = InMemoryStoreManager()
    graphs, servers, gossips = [], [], []
    first = open_graph(dict(cfg), store_manager=shared)
    n = args.replicas or first.config.get("server.fleet.replicas")
    probe_interval = first.config.get("server.fleet.probe-interval-s")
    probe_timeout = first.config.get("server.fleet.probe-timeout-s")
    router = FleetRouter(
        vnodes=first.config.get("server.fleet.vnodes"),
        candidates=first.config.get("server.fleet.candidates"),
        probe_timeout_s=probe_timeout,
        trend_windows=first.config.get("server.fleet.trend-windows"),
    )
    warmup_dir = first.config.get("server.fleet.warmup-dir")
    try:
        for i in range(n):
            graph = first if i == 0 else open_graph(
                dict(cfg), store_manager=shared
            )
            if i > 0:
                graphs.append(graph)
            name = f"r{i}"
            if i > 0 and warmup_dir:
                warm_replica(graph, warmup_dir, replica=name)
            manager = JanusGraphManager()
            manager.put_graph(args.graph_name, graph)
            server = JanusGraphServer(
                manager=manager,
                default_graph=args.graph_name,
                host=args.host,
                port=0,
                replica_name=name,
                # process-global planes (history sampler, SLO engine)
                # belong to ONE owner in an in-process fleet
                history_enabled=(i == 0) and graph.config.get(
                    "metrics.history-enabled"
                ),
                slo_enabled=(i == 0) and graph.config.get(
                    "metrics.slo-enabled"
                ),
                # like history/SLO: the sampler, watchdog, and bundle
                # plane are process-global — replica 0 owns them
                profiler_enabled=(i == 0) and graph.config.get(
                    "metrics.profile-enabled"
                ),
                watchdog_enabled=(i == 0) and graph.config.get(
                    "server.watchdog-enabled"
                ),
                bundle_dir=(
                    graph.config.get("metrics.bundle-dir") if i == 0
                    else ""
                ),
            ).start()
            servers.append(server)
            gossip = StateGossip(
                name, server.admission,
                fanout=graph.config.get("server.fleet.gossip-fanout"),
                timeout_s=probe_timeout,
            )
            server.gossip = gossip
            gossips.append(gossip)
            router.add_replica(name, args.host, server.port)
        urls = [f"http://{args.host}:{s.port}" for s in servers]
        for i, gossip in enumerate(gossips):
            gossip.set_peers([u for j, u in enumerate(urls) if j != i])
            gossip.start(
                interval_s=first.config.get(
                    "server.fleet.gossip-interval-s"
                )
            )
        router.probe()
        router.start_probes(interval_s=probe_interval)
        federation = None
        if first.config.get("server.fleet.federation-enabled"):
            from janusgraph_tpu.observability.federation import (
                FleetFederation,
            )

            federation = FleetFederation(
                router,
                interval_s=first.config.get(
                    "server.fleet.federation-interval-s"
                ),
                timeout_s=first.config.get(
                    "server.fleet.federation-timeout-s"
                ),
                retention=first.config.get("metrics.fleet-retention"),
                outlier_metric=first.config.get(
                    "metrics.fleet-outlier-metric"
                ),
                outlier_factor=first.config.get(
                    "metrics.fleet-outlier-factor"
                ),
                outlier_min_count=first.config.get(
                    "metrics.fleet-outlier-min-count"
                ),
                push_enabled=first.config.get("server.fleet.push-enabled"),
                ship_bundles=first.config.get(
                    "server.fleet.push-ship-bundles"
                ),
                bundle_retention=first.config.get(
                    "server.fleet.push-bundle-retention"
                ),
                bundle_min_interval_s=first.config.get(
                    "server.fleet.push-bundle-min-interval-s"
                ),
            )
            federation.start()
        frontend = FleetFrontend(
            router, host=args.host, port=args.port,
            federation=federation,
        ).start()
        for server in servers:
            print(f"  replica {server.replica_name}: "
                  f"{args.host}:{server.port}")
        print(f"fleet frontend listening on {args.host}:{frontend.port} "
              f"({n} replicas)")
        try:
            import threading

            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            frontend.stop()
            if federation is not None:
                federation.stop()
    finally:
        router.stop()
        for gossip in gossips:
            gossip.stop()
        for server in servers:
            server.stop()
        for graph in graphs:
            graph.close()
        first.close()
    return 0


def _slo_specs_from_config(cfg):
    """The stock SLO spec set sized from the metrics.slo-* keys."""
    from janusgraph_tpu.observability.slo import default_specs

    return default_specs(
        availability_objective=cfg.get("metrics.slo-availability-objective"),
        latency_objective=cfg.get("metrics.slo-latency-objective"),
        latency_threshold_ms=cfg.get("metrics.slo-latency-threshold-ms"),
        freshness_max_staleness=cfg.get(
            "metrics.slo-freshness-max-staleness"
        ),
        fast_windows=cfg.get("metrics.slo-fast-windows"),
        slow_windows=cfg.get("metrics.slo-slow-windows"),
        page_burn=cfg.get("metrics.slo-page-burn"),
        ticket_burn=cfg.get("metrics.slo-ticket-burn"),
    )


def cmd_console(args) -> int:
    banner = "JanusGraph-TPU console — `g` is the traversal source, `P` the predicates"
    ns = {}
    if args.remote:
        from janusgraph_tpu.driver import JanusGraphClient

        host, _, port = args.remote.partition(":")
        client = JanusGraphClient(host=host, port=int(port or 8182))
        ns["client"] = client
        ns["submit"] = client.submit
        banner = (
            "JanusGraph-TPU remote console — submit('g.V()...') runs on "
            f"{args.remote}"
        )
    else:
        from janusgraph_tpu.core.codecs import Direction
        from janusgraph_tpu.core.graph import open_graph
        from janusgraph_tpu.core.traversal import (
            P,
            Pick,
            T,
            __ as _anon,
        )

        graph = open_graph(_load_config(args.config))
        if args.load_gods:
            from janusgraph_tpu.core import gods

            gods.load(graph)
        ns.update({
            "graph": graph, "g": graph.traversal(), "P": P, "__": _anon,
            "T": T, "Direction": Direction, "Pick": Pick,
        })
    code.interact(banner=banner, local=ns)
    return 0


def cmd_bench(args) -> int:
    import os

    if args.scale:
        os.environ["BENCH_SCALE"] = str(args.scale)
    root = os.path.join(os.path.dirname(__file__), "..")
    sys.path.insert(0, root)
    import bench

    bench.main()
    return 0


def cmd_storage_server(args) -> int:
    """Serve a storage backend over TCP (the remote KCVS endpoint other
    instances open with storage.backend=remote)."""
    from janusgraph_tpu.storage.remote import RemoteStoreServer

    if args.directory:
        from janusgraph_tpu.storage.localstore import open_local_kcvs

        manager = open_local_kcvs(args.directory)
        kind = f"local({args.directory})"
    elif args.sharded_nodes is not None:
        if args.sharded_nodes < 1:
            print("--sharded-nodes must be >= 1", file=sys.stderr)
            return 2
        from janusgraph_tpu.storage.sharded_store import ShardedStoreManager

        manager = ShardedStoreManager(num_nodes=args.sharded_nodes)
        kind = f"sharded({args.sharded_nodes})"
    else:
        from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

        manager = InMemoryStoreManager()
        kind = "inmemory"
    server = RemoteStoreServer(manager, host=args.host, port=args.port).start()
    host, port = server.address
    print(f"storage server ({kind}) listening on {host}:{port}", flush=True)
    print(
        "connect with open_graph({'storage.backend': 'remote', "
        f"'storage.hostname': '{host}', 'storage.port': {port}}})",
        flush=True,
    )
    try:
        import time as _t

        while True:
            _t.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_telemetry(args) -> int:
    """Dump telemetry: Prometheus text (default) or the JSON snapshot.
    With --url, scrape a RUNNING server's /metrics (or /telemetry with
    --json); without, render this process's registry — useful from
    scripts/consoles that imported the package and did work."""
    if args.url:
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        path = "/telemetry" if args.json else "/metrics"
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            sys.stdout.write(resp.read().decode("utf-8"))
        return 0
    from janusgraph_tpu.observability import (
        json_snapshot,
        prometheus_text,
        registry,
        tracer,
    )

    if args.json:
        print(json.dumps(json_snapshot(registry, tracer), indent=2,
                         default=str))
    else:
        sys.stdout.write(prometheus_text(registry))
    return 0


def cmd_trace(args) -> int:
    """Print every retained span tree of one trace id (the stitched
    cross-process view): local process registry by default, or a running
    server's /telemetry snapshot with --url."""
    try:
        trace_id = f"{int(args.trace_id, 16):016x}"
    except ValueError:
        print(f"not a hex trace id: {args.trace_id!r}", file=sys.stderr)
        return 2
    if args.url:
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        with urllib.request.urlopen(base + "/telemetry", timeout=10) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
        trees = [
            s for s in payload.get("spans", [])
            if s.get("trace_id") == trace_id
        ]
    else:
        from janusgraph_tpu.observability import tracer

        trees = [r.to_dict() for r in tracer.find_trace(trace_id)]
    print(json.dumps({"trace_id": trace_id, "spans": trees}, indent=2,
                     default=str))
    return 0 if trees else 1


def cmd_flight(args) -> int:
    """Dump the black-box flight recorder: the bounded ring of salient
    events (injected faults, breaker transitions, retry exhaustions, torn
    recoveries, checkpoints, OLAP resumes, slow spans). --dump also
    writes a JSON dump file; --url reads a running server's /flight."""
    if args.url:
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        path = "/flight?dump=1" if args.dump else "/flight"
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            sys.stdout.write(resp.read().decode("utf-8"))
            sys.stdout.write("\n")
        return 0
    from janusgraph_tpu.observability import flight_recorder

    if args.dump:
        path = flight_recorder.dump(reason="cli")
        print(f"dumped -> {path}", file=sys.stderr)
    print(json.dumps(flight_recorder.snapshot(), indent=2, default=str))
    return 0


def cmd_top(args) -> int:
    """Print the query-digest table: the top-K traversal shapes by total
    cost (count, total/p50/p95 wall, cells). Local process table by
    default, or a running server's GET /profile with --url."""
    if args.url:
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        with urllib.request.urlopen(base + "/profile", timeout=10) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
        digests = payload.get("digests", [])
    else:
        from janusgraph_tpu.observability.profiler import digest_table
        from janusgraph_tpu.olap.spillover import promoted_digests

        promoted = promoted_digests()
        digests = digest_table.top(args.k)
        for d in digests:
            d["promoted"] = d["digest"] in promoted
    if args.json:
        print(json.dumps({"digests": digests[: args.k]}, indent=2))
        return 0
    print(f"{'digest':10} {'count':>7} {'total_ms':>10} {'p50_ms':>8} "
          f"{'p95_ms':>8} {'cells':>9}  shape")
    for d in digests[: args.k]:
        # spillover-promoted shapes (running on the OLAP executor) are
        # marked like GET /profile marks them
        mark = "*" if d.get("promoted") else " "
        print(f"{d['digest']:9}{mark} {d['count']:>7} "
              f"{d['total_ms']:>10.2f} "
              f"{d['p50_ms']:>8.2f} {d['p95_ms']:>8.2f} "
              f"{d['total_cells']:>9}  {d['shape']}")
    return 0


def cmd_flame(args) -> int:
    """Render one stitched trace's span trees to collapsed-stack lines
    (pipe into any flamegraph renderer). Local tracer by default, or a
    running server's GET /profile/flame with --url. --live renders the
    continuous sampling profiler's merged flame windows instead — what
    every thread was actually doing, no instrumentation required."""
    if args.live:
        if args.url:
            import urllib.error
            import urllib.request

            base = args.url.rstrip("/")
            if not base.startswith("http"):
                base = "http://" + base
            try:
                with urllib.request.urlopen(
                    base + f"/debug/profile?window={args.window}",
                    timeout=10,
                ) as resp:
                    sys.stdout.write(resp.read().decode("utf-8"))
                return 0
            except urllib.error.HTTPError as e:
                print(f"server: {e}", file=sys.stderr)
                return 1
        from janusgraph_tpu.observability import sampling_profiler

        text = sampling_profiler.flame_text(last=args.window)
        if not text:
            print("no samples collected (is the profiler running?)",
                  file=sys.stderr)
            return 1
        print(text)
        return 0
    if not args.trace_id:
        print("trace_id required (or --live for the sampling profiler)",
              file=sys.stderr)
        return 2
    try:
        trace_id = f"{int(args.trace_id, 16):016x}"
    except ValueError:
        print(f"not a hex trace id: {args.trace_id!r}", file=sys.stderr)
        return 2
    if args.url:
        import urllib.error
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        try:
            with urllib.request.urlopen(
                base + f"/profile/flame?trace={trace_id}", timeout=10
            ) as resp:
                sys.stdout.write(resp.read().decode("utf-8"))
            return 0
        except urllib.error.HTTPError as e:
            print(f"server: {e}", file=sys.stderr)
            return 1
    from janusgraph_tpu.observability import tracer
    from janusgraph_tpu.observability.profiler import flame_text

    text = flame_text(tracer, trace_id)
    if not text:
        print(f"trace {trace_id} not retained", file=sys.stderr)
        return 1
    print(text)
    return 0


def cmd_bundle(args) -> int:
    """Fetch the newest anomaly forensics bundle — flame windows, the
    flight ring, the timeseries tail, all-thread stacks, in-flight
    requests — from a running server's GET /debug/bundle with --url, or
    this process's bundle directory. --capture forces a fresh capture
    first (rate limit bypassed)."""
    if args.url:
        import urllib.error
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        path = "/debug/bundle?capture=1" if args.capture else "/debug/bundle"
        try:
            with urllib.request.urlopen(base + path, timeout=30) as resp:
                sys.stdout.write(resp.read().decode("utf-8"))
                sys.stdout.write("\n")
            return 0
        except urllib.error.HTTPError as e:
            print(f"server: {e}", file=sys.stderr)
            return 1
    from janusgraph_tpu.observability import bundle_writer

    if args.capture:
        path = bundle_writer.capture(reason="cli", force=True)
        if path is None:
            print("capture failed (is metrics.bundle-dir set?)",
                  file=sys.stderr)
            return 1
        print(f"captured -> {path}", file=sys.stderr)
    got = bundle_writer.latest()
    if got is None:
        print("no bundle on disk (set metrics.bundle-dir, or --capture)",
              file=sys.stderr)
        return 1
    print(json.dumps(got, indent=2, default=str))
    return 0


def cmd_timeseries(args) -> int:
    """Query the metrics history ring: per-window counter/timer deltas
    with window percentiles. Local process ring by default, a running
    server's GET /timeseries with --url; --export writes the retained
    windows as JSONL for offline analysis."""
    if args.url:
        import urllib.parse
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        qs = urllib.parse.urlencode(
            {"name": args.name, "window": args.window}
        )
        with urllib.request.urlopen(
            base + "/timeseries?" + qs, timeout=10
        ) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
    else:
        from janusgraph_tpu.observability import history

        if args.export:
            n = history.export_jsonl(args.export, last=args.window)
            print(f"exported {n} windows -> {args.export}", file=sys.stderr)
        payload = history.query(name=args.name, window=args.window)
    print(json.dumps(payload, indent=2, default=str))
    return 0


def cmd_incident(args) -> int:
    """Pull a fleet frontend's merged incident report (GET
    /fleet/incident): every replica's flight ring, offset-corrected onto
    one clock and causally ordered, with the failover narrative
    (kill -> mark_dead -> re-pin -> warm-up) and a Chrome-trace document
    (one lane per replica). --trace-out writes the trace JSON for
    chrome://tracing / ui.perfetto.dev; --json prints the full payload."""
    import urllib.request

    base = args.url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    url = base + f"/fleet/incident?window={args.window}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            json.dump(payload.get("trace", {}), f, indent=2, default=str)
        print(f"trace -> {args.trace_out}", file=sys.stderr)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0
    events = payload.get("events", [])
    print(f"incident window: last {payload.get('window_s')}s  "
          f"replicas: {', '.join(payload.get('replicas', [])) or '-'}  "
          f"events: {len(events)}"
          + ("  PARTIAL (missing: "
             + ", ".join(payload.get("missing", [])) + ")"
             if payload.get("partial") else ""))
    for p in payload.get("phases", []):
        print(f"  {p['phase']:>10}  t={p['ts_corrected']:.6f}  "
              f"lane={p['lane'] or '-'}  {p.get('detail') or ''}")
    for e in events[-args.tail:] if args.tail else events:
        detail = e.get("action") or e.get("kind") or ""
        print(f"  {e['ts_corrected']:.6f}  [{e['lane'] or '-':>8}]  "
              f"{e.get('category')}{':' + str(detail) if detail else ''}")
    return 0


def cmd_watch(args) -> int:
    """Live-tail a server's telemetry bus over the /watch WebSocket
    (observability/stream.py): flight events, sealed metrics windows,
    SLO transitions, flame-window seals, and bundle announcements as
    they happen — no polling. --cursor resumes a stream past an
    already-seen seq (the federation's cursor vocabulary), --names
    prefix-filters, and heartbeats keep quiet streams distinguishable
    from dead servers."""
    from janusgraph_tpu.driver.client import WatchSession

    subscribe = {"name": "cli-watch"}
    if args.streams:
        subscribe["streams"] = [
            s.strip() for s in args.streams.split(",") if s.strip()
        ]
    if args.names:
        subscribe["names"] = [
            s.strip() for s in args.names.split(",") if s.strip()
        ]
    if args.cursor:
        cursors = {}
        for pair in args.cursor:
            stream, _, seq = pair.partition("=")
            try:
                cursors[stream] = int(seq)
            except ValueError:
                print(f"bad --cursor {pair!r} (want stream=seq)",
                      file=sys.stderr)
                return 2
        subscribe["cursors"] = cursors
    if args.heartbeat:
        subscribe["heartbeat_s"] = args.heartbeat
    try:
        session = WatchSession(
            args.url, subscribe=subscribe, connect_timeout_s=5.0
        )
    except (OSError, ConnectionError) as e:
        print(f"connect failed: {e}", file=sys.stderr)
        return 1
    seen = 0
    try:
        while True:
            try:
                frame = session.recv(timeout=2.0)
            except ConnectionError as e:
                print(f"stream closed: {e}", file=sys.stderr)
                return 1
            if frame is None:
                continue
            if args.json:
                print(json.dumps(frame, default=str))
                sys.stdout.flush()
            else:
                kind = frame.get("type")
                if kind == "hello":
                    print(f"# watching {frame.get('replica') or '-'}  "
                          f"streams={','.join(frame.get('streams', []))}  "
                          f"cursors={frame.get('cursors')}",
                          file=sys.stderr)
                elif kind == "heartbeat":
                    if args.heartbeats:
                        print(f"# heartbeat dropped={frame.get('dropped')}",
                              file=sys.stderr)
                elif kind == "event":
                    data = frame.get("data") or {}
                    detail = (
                        data.get("category")
                        or f"window counters={len(data.get('counters') or {})}"
                        f" series={len(data.get('series') or {})}"
                    )
                    extra = data.get("action") or data.get("kind") or ""
                    print(f"[{frame.get('stream'):>7} "
                          f"#{frame.get('seq')}] {detail}"
                          + (f":{extra}" if extra else ""))
                    sys.stdout.flush()
                else:
                    print(json.dumps(frame, default=str), file=sys.stderr)
            if frame.get("type") == "event":
                seen += 1
                if args.count and seen >= args.count:
                    return 0
    except KeyboardInterrupt:
        return 0
    finally:
        session.close()


def cmd_fleet_bundles(args) -> int:
    """List or fetch forensics bundles a fleet frontend shipped
    off-host (GET /fleet/bundles): bundles announced on each replica's
    telemetry bus are retained at the frontend, so a dead replica's
    evidence is still retrievable here."""
    import urllib.request

    base = args.url.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    url = base + "/fleet/bundles"
    if args.replica:
        url += f"?replica={args.replica}&i={args.index}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.replica or args.json:
        print(json.dumps(payload, indent=2, default=str))
        return 0
    rows = payload.get("bundles", [])
    push = payload.get("push", {})
    print(f"shipped bundles: {len(rows)}  "
          f"(fetched={payload.get('fetched')} "
          f"rate-limited-skips={payload.get('rate_skipped')}  "
          f"push channels={len(push.get('channels') or {})})")
    for b in rows:
        print(f"  {b.get('replica'):>10}  "
              f"reason={b.get('reason') or '-'}  "
              f"path={b.get('path') or '-'}  "
              f"fetched_at={b.get('fetched_at')}")
    return 0


def cmd_timeline(args) -> int:
    """Render one retained OLAP run to Chrome-trace (catapult) JSON —
    load the output in chrome://tracing or ui.perfetto.dev to see
    exchange/compute/checkpoint overlap per superstep per shard. Local
    run records by default, a server's GET /profile/timeline with
    --url."""
    if args.url:
        import urllib.error
        import urllib.request

        base = args.url.rstrip("/")
        if not base.startswith("http"):
            base = "http://" + base
        try:
            with urllib.request.urlopen(
                base + f"/profile/timeline?run={args.run}", timeout=10
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            print(f"server: {e}", file=sys.stderr)
            return 1
    else:
        from janusgraph_tpu.observability import registry, render_run

        doc = render_run(registry, run=args.run)
        if doc is None:
            print(f"no retained OLAP run at index {args.run}",
                  file=sys.stderr)
            return 1
    text = json.dumps(doc, indent=None if args.out else 2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


def cmd_benchdiff(args) -> int:
    """Compare two bench artifacts cell-by-cell (stage, scale, platform,
    host-fallback): per-metric deltas with improve/regress/noise
    verdicts. With --fail-on-regress, exit non-zero when any cell
    regressed — the CI gate (bin/benchdiff.sh wraps this)."""
    from janusgraph_tpu.observability.benchdiff import diff_artifacts

    for p in (args.old, args.new):
        if not os.path.isfile(p):
            print(f"no such artifact: {p}", file=sys.stderr)
            return 2
    report = diff_artifacts(
        args.old, args.new, threshold=args.threshold / 100.0
    )
    print(json.dumps(report, indent=None if args.compact else 2))
    if report["cells_compared"] == 0:
        print("benchdiff: no comparable cells (stage/scale/platform "
              "mismatch?)", file=sys.stderr)
        return 3
    if args.fail_on_regress and report["regressed"]:
        regressed = [
            c["cell"] for c in report["comparisons"]
            if c["verdict"] == "regress"
        ]
        print(f"benchdiff: REGRESSION in cells {regressed}",
              file=sys.stderr)
        return 1
    return 0


def cmd_chaos(args) -> int:
    """Seeded chaos soak on an inmemory graph: drive an OLTP workload (and
    optionally PageRank) through injected faults including a torn batch,
    then reopen, run torn-commit recovery, and print a JSON report. The
    operator-facing smoke test for the self-healing paths
    (docs/robustness.md has the full recipe)."""
    import tempfile
    import time as _t

    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.exceptions import (
        InjectedCrashError,
        TemporaryBackendError,
    )
    from janusgraph_tpu.observability import registry
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    base = {
        "ids.authority-wait-ms": 0.0,
        "locks.wait-ms": 0.0,
        "tx.log-tx": True,
        "tx.max-commit-time-ms": 0.0,
        "storage.scan-parallelism": 1,
        "storage.backoff-base-ms": 1.0,
        "storage.backoff-max-ms": 4.0,
        "computer.executor": "cpu",
        "computer.checkpoint-every": 2,
        "computer.checkpoint-path": tempfile.mktemp(suffix=".npz"),
    }
    torn_at = max(8, args.txs // 2)
    chaos = {
        **base,
        "storage.faults.enabled": True,
        "storage.faults.seed": args.seed,
        "storage.faults.read-error-rate": args.error_rate,
        "storage.faults.write-error-rate": args.error_rate,
        "storage.faults.torn-mutation-at": torn_at,
        "storage.faults.lock-expiry-at": max(2, args.txs // 3),
        "storage.faults.preempt-superstep": 3,
    }
    mgr = InMemoryStoreManager()
    t0 = _t.monotonic()
    graph = JanusGraphTPU(chaos, store_manager=mgr)
    plan = graph.fault_plan
    mgmt = graph.management()
    mgmt.make_property_key("uid", int)
    mgmt.build_composite_index("byUid", ["uid"], unique=True)

    def write(i):
        retries = 12
        for attempt in range(retries):
            tx = graph.new_transaction()
            try:
                tx.add_vertex(uid=i)
                tx.commit()
                return
            except TemporaryBackendError:
                if tx.is_open:
                    tx.rollback()
                if attempt == retries - 1:
                    raise

    crashed_at = None
    for i in range(args.txs):
        try:
            write(i)
        except InjectedCrashError:
            crashed_at = i
            break
    # "crash": abandon the graph un-closed, reopen, self-heal
    t_rec = _t.monotonic()
    graph2 = JanusGraphTPU(base, store_manager=mgr)
    recovery_ms = (_t.monotonic() - t_rec) * 1000.0
    if crashed_at is not None:
        for i in range(crashed_at + 1, args.txs):
            write_tx = graph2.new_transaction()
            write_tx.add_vertex(uid=i)
            write_tx.commit()
    tx = graph2.new_transaction(read_only=True)
    present = sum(
        1 for i in range(args.txs)
        if graph2.index_lookup(tx, "byUid", (i,))
    )
    tx.rollback()
    snap = registry.snapshot()
    injected: dict = {}
    for e in plan.journal:
        injected[e["kind"]] = injected.get(e["kind"], 0) + 1
    report = {
        "seed": args.seed,
        "txs": args.txs,
        "crashed_at": crashed_at,
        "vertices_present": present,
        "torn_recovery": graph2.last_torn_recovery,
        "injected": injected,
        "ops_observed": plan.counters(),
        "journal": plan.journal[:64],
        "retries": snap.get("storage.backend_op.retries", {}).get("count", 0),
        "recovery_open_ms": round(recovery_ms, 2),
        "wall_s": round(_t.monotonic() - t0, 3),
    }
    print(json.dumps(report, indent=None if args.compact else 2))
    graph2.close()
    return 0 if present == args.txs else 1


def cmd_config_docs(args) -> int:
    from janusgraph_tpu.core.config import describe_options

    text = (
        "# Configuration reference\n\n"
        "Generated from the registered option tree "
        "(`janusgraph_tpu/core/config.py`; reference model: the reference's "
        "auto-generated janusgraph-cfg.md).\n\n" + describe_options() + "\n"
    )
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        # identical bytes on both paths: `print` would append a second
        # newline and make regenerated docs churn a trailing blank line
        sys.stdout.write(text)
    return 0


def cmd_export(args) -> int:
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphml, export_graphson

    fn = export_graphml if args.format == "graphml" else export_graphson
    graph = open_graph(_load_config(args.config))
    try:
        counts = fn(graph, args.out)
        print(f"exported {counts['vertices']} vertices, "
              f"{counts['edges']} edges -> {args.out}")
    finally:
        graph.close()
    return 0


def cmd_import(args) -> int:
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import import_graphml, import_graphson

    if args.batch < 1:
        print("--batch must be >= 1", file=sys.stderr)
        return 2
    fn = import_graphml if args.format == "graphml" else import_graphson
    graph = open_graph(_load_config(args.config))
    try:
        counts = fn(graph, args.infile, batch_size=args.batch)
        print(f"imported {counts['vertices']} vertices, "
              f"{counts['edges']} edges from {args.infile}")
    finally:
        graph.close()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="janusgraph_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    ps = sub.add_parser("server", help="start the query server")
    ps.add_argument("--config", help="graph config JSON file")
    ps.add_argument("--graph-name", default="graph")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=8182)
    ps.add_argument("--auth-credentials", help="credentials-graph config JSON")
    ps.add_argument("--load-gods", action="store_true",
                    help="preload the Graph of the Gods example")
    ps.add_argument("--replica-name", default="",
                    help="fleet identity tag (overrides "
                         "server.fleet.replica-name)")
    ps.set_defaults(fn=cmd_server)

    pfleet = sub.add_parser(
        "fleet",
        help="run N server replicas over one shared backend behind the "
             "fleet router (probes, gossip, drain, warm-up)",
    )
    pfleet.add_argument("--config", help="graph config JSON file")
    pfleet.add_argument("--graph-name", default="graph")
    pfleet.add_argument("--host", default="127.0.0.1")
    pfleet.add_argument("--port", type=int, default=8182,
                        help="frontend port (replicas pick free ports)")
    pfleet.add_argument("--replicas", type=int, default=0,
                        help="replica count (0 = server.fleet.replicas)")
    pfleet.set_defaults(fn=cmd_fleet)

    pc = sub.add_parser("console", help="interactive console")
    pc.add_argument("--config", help="graph config JSON file")
    pc.add_argument("--remote", help="host:port of a running server")
    pc.add_argument("--load-gods", action="store_true")
    pc.set_defaults(fn=cmd_console)

    pb = sub.add_parser("bench", help="run the benchmark")
    pb.add_argument("--scale", type=int)
    pb.set_defaults(fn=cmd_bench)

    pss = sub.add_parser(
        "storage-server", help="serve a storage backend over TCP"
    )
    pss.add_argument("--host", default="127.0.0.1")
    pss.add_argument("--port", type=int, default=0)
    backing = pss.add_mutually_exclusive_group()
    backing.add_argument("--directory", help="persistent local store directory")
    backing.add_argument(
        "--sharded-nodes", type=int,
        help="serve an N-node sharded composite (N >= 1)",
    )
    pss.set_defaults(fn=cmd_storage_server)

    pt = sub.add_parser(
        "telemetry",
        help="dump telemetry (Prometheus text, or JSON with --json)",
    )
    pt.add_argument(
        "--url", help="scrape a running server (host:port or http URL) "
        "instead of this process's registry",
    )
    pt.add_argument("--json", action="store_true",
                    help="JSON snapshot (metrics + spans + slow ops)")
    pt.set_defaults(fn=cmd_telemetry)

    ptr = sub.add_parser(
        "trace",
        help="print the span trees of one trace id (stitched view)",
    )
    ptr.add_argument("trace_id", help="16-hex-char trace id")
    ptr.add_argument(
        "--url", help="read a running server's /telemetry instead of "
        "this process's tracer",
    )
    ptr.set_defaults(fn=cmd_trace)

    pf = sub.add_parser(
        "flight",
        help="dump the black-box flight recorder (salient-event ring)",
    )
    pf.add_argument(
        "--url", help="read a running server's /flight instead of this "
        "process's recorder",
    )
    pf.add_argument("--dump", action="store_true",
                    help="also write a JSON dump file")
    pf.set_defaults(fn=cmd_flight)

    ptp = sub.add_parser(
        "top",
        help="print the query-digest table (top shapes by total cost)",
    )
    ptp.add_argument(
        "--url", help="read a running server's /profile instead of this "
        "process's table",
    )
    ptp.add_argument("-k", type=int, default=10, help="rows to print")
    ptp.add_argument("--json", action="store_true")
    ptp.set_defaults(fn=cmd_top)

    pfl = sub.add_parser(
        "flame",
        help="render one trace to collapsed-stack flamegraph lines",
    )
    pfl.add_argument("trace_id", nargs="?", default="",
                     help="16-hex-char trace id (omit with --live)")
    pfl.add_argument(
        "--url", help="read a running server's /profile/flame (or "
        "/debug/profile with --live) instead of this process",
    )
    pfl.add_argument(
        "--live", action="store_true",
        help="render the continuous sampling profiler's flame windows "
        "instead of one trace",
    )
    pfl.add_argument("--window", type=int, default=0,
                     help="with --live: last N flame windows (0 = all)")
    pfl.set_defaults(fn=cmd_flame)

    pbu = sub.add_parser(
        "bundle",
        help="fetch the newest anomaly forensics bundle",
    )
    pbu.add_argument(
        "--url", help="read a running server's /debug/bundle instead of "
        "this process's bundle directory",
    )
    pbu.add_argument("--capture", action="store_true",
                     help="force a fresh capture first")
    pbu.set_defaults(fn=cmd_bundle)

    pts = sub.add_parser(
        "timeseries",
        help="query the metrics history (per-window deltas/percentiles)",
    )
    pts.add_argument(
        "--url", help="read a running server's /timeseries instead of "
        "this process's history ring",
    )
    pts.add_argument("--name", default="",
                     help="metric-name prefix filter")
    pts.add_argument("--window", type=int, default=0,
                     help="last N windows only (0 = all retained)")
    pts.add_argument("--export",
                     help="also write retained windows to this JSONL file")
    pts.set_defaults(fn=cmd_timeseries)

    ptl = sub.add_parser(
        "timeline",
        help="render one OLAP run to Chrome-trace (catapult) JSON",
    )
    ptl.add_argument(
        "--url", help="read a running server's /profile/timeline instead "
        "of this process's run records",
    )
    ptl.add_argument("--run", type=int, default=-1,
                     help="run record index (negative = from the end)")
    ptl.add_argument("--out", help="write the trace JSON to this file")
    ptl.set_defaults(fn=cmd_timeline)

    pin = sub.add_parser(
        "incident",
        help="merged cross-replica failover forensics from a fleet "
             "frontend (/fleet/incident)",
    )
    pin.add_argument(
        "--url", required=True,
        help="fleet frontend base URL (host:port)",
    )
    pin.add_argument(
        "--window", type=float, default=60.0,
        help="lookback seconds (0 = whole flight rings)",
    )
    pin.add_argument(
        "--trace-out", help="write the Chrome-trace JSON to this file",
    )
    pin.add_argument("--json", action="store_true",
                     help="print the full report payload")
    pin.add_argument(
        "--tail", type=int, default=0,
        help="print only the last N merged events (0 = all)",
    )
    pin.set_defaults(fn=cmd_incident)

    pw = sub.add_parser(
        "watch",
        help="live-tail a server's telemetry bus (/watch WebSocket)",
    )
    pw.add_argument(
        "--url", required=True, help="server base URL (host:port)",
    )
    pw.add_argument(
        "--streams",
        help="comma-separated streams (flight,window,slo,flame,bundle; "
             "default all)",
    )
    pw.add_argument(
        "--names",
        help="comma-separated name/category prefixes to filter on",
    )
    pw.add_argument(
        "--cursor", action="append", default=[],
        metavar="STREAM=SEQ",
        help="resume a stream past an already-seen seq (repeatable)",
    )
    pw.add_argument(
        "--heartbeat", type=float, default=0.0,
        help="requested heartbeat cadence in seconds (0 = server default)",
    )
    pw.add_argument("--count", type=int, default=0,
                    help="exit after N events (0 = run until interrupted)")
    pw.add_argument("--json", action="store_true",
                    help="print raw protocol frames as JSON lines")
    pw.add_argument("--heartbeats", action="store_true",
                    help="also print heartbeat frames (compact mode)")
    pw.set_defaults(fn=cmd_watch)

    pfb = sub.add_parser(
        "fleet-bundles",
        help="forensics bundles shipped off-host to a fleet frontend "
             "(/fleet/bundles)",
    )
    pfb.add_argument(
        "--url", required=True,
        help="fleet frontend base URL (host:port)",
    )
    pfb.add_argument("--replica",
                     help="fetch one replica's full bundle body")
    pfb.add_argument(
        "--index", type=int, default=-1,
        help="which of the replica's retained bundles (-1 = newest)",
    )
    pfb.add_argument("--json", action="store_true",
                     help="print the raw listing payload")
    pfb.set_defaults(fn=cmd_fleet_bundles)

    pbd = sub.add_parser(
        "benchdiff",
        help="compare two bench artifacts (improve/regress/noise verdicts)",
    )
    pbd.add_argument("old", help="prior artifact (JSON or JSONL)")
    pbd.add_argument("new", help="new artifact (JSON or JSONL)")
    pbd.add_argument(
        "--threshold", type=float, default=10.0,
        help="relative noise threshold in percent (default 10)",
    )
    pbd.add_argument(
        "--fail-on-regress", action="store_true",
        help="exit 1 when any cell regressed (the CI gate)",
    )
    pbd.add_argument("--compact", action="store_true",
                     help="one-line JSON report")
    pbd.set_defaults(fn=cmd_benchdiff)

    pch = sub.add_parser(
        "chaos",
        help="seeded chaos soak: inject faults, crash, self-heal, report",
    )
    pch.add_argument("--seed", type=int, default=42)
    pch.add_argument("--txs", type=int, default=120)
    pch.add_argument("--error-rate", type=float, default=0.01,
                     help="per-op probability of injected temporary faults")
    pch.add_argument("--compact", action="store_true",
                     help="one-line JSON report")
    pch.set_defaults(fn=cmd_chaos)

    pd = sub.add_parser("config-docs", help="render the config reference")
    pd.add_argument("--out", help="write to this file instead of stdout")
    pd.set_defaults(fn=cmd_config_docs)

    pe = sub.add_parser(
        "export", help="export a graph (GraphSON or GraphML)"
    )
    # required: a no-config export would truncate the output with a fresh
    # (empty) in-memory graph's contents
    pe.add_argument("--config", required=True, help="graph config JSON file")
    pe.add_argument(
        "--format", choices=("graphson", "graphml"), default="graphson",
        help="interchange format (graphml: primitive values only)",
    )
    pe.add_argument("out", help="output path")
    pe.set_defaults(fn=cmd_export)

    pi = sub.add_parser(
        "import", help="import GraphSON or GraphML into a graph"
    )
    # required: importing into an unnamed in-memory graph that closes right
    # after would silently discard everything
    pi.add_argument("--config", required=True, help="graph config JSON file")
    pi.add_argument(
        "--batch", type=int, default=1000,
        help="elements per import transaction (>= 1)",
    )
    pi.add_argument(
        "--format", choices=("graphson", "graphml"), default="graphson",
        help="interchange format",
    )
    pi.add_argument("infile", help="input path")
    pi.set_defaults(fn=cmd_import)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
