from janusgraph_tpu.cli import main

raise SystemExit(main())
