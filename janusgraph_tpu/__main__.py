"""`python -m janusgraph_tpu` entry point.

The __name__ guard matters: without it, merely *importing*
``janusgraph_tpu.__main__`` (pkgutil walkers, the graphlint import sweep,
doc generators) executes the CLI against the importer's argv.
"""

from janusgraph_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
