"""Operation metrics: counters + timers and the instrumented-store wrapper.

Capability parity with the reference's metrics layer
(reference: util/stats/MetricManager.java:36 — Dropwizard registry
singleton; diskstorage/util/MetricInstrumentedStore.java — per-store
counter+timer around every KCVS call, wrapped at Backend.java:184-188;
per-tx metric groups StandardJanusGraphTx.java:258-262; reporters
GraphDatabaseConfiguration.java:1012-1094).

TPU-build shape: a thread-safe in-process registry of counters and
nanosecond timers keyed by dotted names, a console/dict reporter, and a
KCVS decorator timing get_slice/get_slice_multi/mutate/get_keys/
acquire_lock. Backend wraps raw stores BEFORE the cache layer, like the
reference, so cache hits are visible as the difference between tx-level
and store-level call counts (the property JanusGraphOperationCountingTest
asserts)."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    StoreTransaction,
)


class Counter:
    __slots__ = ("count", "_lock")

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def inc(self, delta: int = 1) -> None:
        with self._lock:
            self.count += delta


class Timer:
    __slots__ = ("count", "total_ns", "max_ns", "_lock")

    def __init__(self):
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0
        self._lock = threading.Lock()

    def update(self, elapsed_ns: int) -> None:
        with self._lock:
            self.count += 1
            self.total_ns += elapsed_ns
            if elapsed_ns > self.max_ns:
                self.max_ns = elapsed_ns

    @property
    def mean_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0


class MetricManager:
    """The registry (reference: MetricManager.java:36). One process-wide
    instance lives at `janusgraph_tpu.util.metrics`; graphs can also carry
    private managers (per-tx groups use name prefixes instead)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter())
        return c

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.timer(name).update(time.perf_counter_ns() - t0)

    # ------------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, dict]:
        with self._lock:  # stable view while writers insert first-seen names
            counters = dict(self._counters)
            timers = dict(self._timers)
        out: Dict[str, dict] = {}
        for name, c in sorted(counters.items()):
            out[name] = {"type": "counter", "count": c.count}
        for name, t in sorted(timers.items()):
            out[name] = {
                "type": "timer",
                "count": t.count,
                "total_ms": t.total_ns / 1e6,
                "mean_ms": t.mean_ms,
                "max_ms": t.max_ns / 1e6,
            }
        return out

    def report(self) -> str:
        """Console reporter (reference: console reporter config
        GraphDatabaseConfiguration.java:1012)."""
        lines = [f"{'name':50} {'count':>10} {'mean_ms':>10} {'total_ms':>10}"]
        for name, m in self.snapshot().items():
            if m["type"] == "counter":
                lines.append(f"{name:50} {m['count']:>10}")
            else:
                lines.append(
                    f"{name:50} {m['count']:>10} {m['mean_ms']:>10.3f} "
                    f"{m['total_ms']:>10.2f}"
                )
        return "\n".join(lines)

    def get_count(self, name: str) -> int:
        c = self._counters.get(name)
        if c is not None:
            return c.count
        t = self._timers.get(name)
        return t.count if t is not None else 0

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


#: process-wide registry (reference: MetricManager.INSTANCE)
metrics = MetricManager()


class MetricInstrumentedStore(KeyColumnValueStore):
    """Times + counts every store operation (reference:
    MetricInstrumentedStore.java — M_GET_SLICE/M_MUTATE/... around each
    call). Metric names: `<prefix>.<store>.<op>`."""

    def __init__(
        self,
        store: KeyColumnValueStore,
        manager: Optional[MetricManager] = None,
        prefix: str = "storage",
    ):
        self._store = store
        self._m = manager if manager is not None else metrics
        self._prefix = f"{prefix}.{store.name}"

    @property
    def name(self) -> str:
        return self._store.name

    @property
    def wrapped(self) -> KeyColumnValueStore:
        return self._store

    def _timed(self, op: str):
        return self._m.time(f"{self._prefix}.{op}")

    def get_slice(self, query, txh: StoreTransaction):
        with self._timed("getSlice"):
            return self._store.get_slice(query, txh)

    def get_slice_multi(self, keys, query, txh: StoreTransaction):
        with self._timed("getSliceMulti"):
            return self._store.get_slice_multi(keys, query, txh)

    def mutate(self, key, additions, deletions, txh: StoreTransaction):
        with self._timed("mutate"):
            return self._store.mutate(key, additions, deletions, txh)

    def acquire_lock(self, key, column, expected, txh: StoreTransaction):
        with self._timed("acquireLock"):
            return self._store.acquire_lock(key, column, expected, txh)

    def get_keys(self, query, txh: StoreTransaction):
        # time only the store's own fetch work (per-next), not the consumer's
        # per-row processing; one timer update per scan, recorded even if the
        # consumer abandons the iterator
        name = f"{self._prefix}.getKeys"
        total = 0
        it = self._store.get_keys(query, txh)
        try:
            while True:
                t0 = time.perf_counter_ns()
                try:
                    item = next(it)
                except StopIteration:
                    total += time.perf_counter_ns() - t0
                    return
                total += time.perf_counter_ns() - t0
                yield item
        finally:
            self._m.timer(name).update(total)

    def close(self) -> None:
        self._store.close()
