"""Operation metrics: counters + timers and the instrumented-store wrapper.

Capability parity with the reference's metrics layer
(reference: util/stats/MetricManager.java:36 — Dropwizard registry
singleton; diskstorage/util/MetricInstrumentedStore.java — per-store
counter+timer around every KCVS call, wrapped at Backend.java:184-188;
per-tx metric groups StandardJanusGraphTx.java:258-262; reporters
GraphDatabaseConfiguration.java:1012-1094).

TPU-build shape: a thread-safe in-process registry of counters and
nanosecond timers keyed by dotted names, a console/dict reporter, and a
KCVS decorator timing get_slice/get_slice_multi/mutate/get_keys/
acquire_lock. Backend wraps raw stores BEFORE the cache layer, like the
reference, so cache hits are visible as the difference between tx-level
and store-level call counts (the property JanusGraphOperationCountingTest
asserts)."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    StoreTransaction,
)


class Counter:
    __slots__ = ("count", "_lock")

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def inc(self, delta: int = 1) -> None:
        with self._lock:
            self.count += delta


class Timer:
    __slots__ = ("count", "total_ns", "max_ns", "_lock")

    def __init__(self):
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0
        self._lock = threading.Lock()

    def update(self, elapsed_ns: int) -> None:
        with self._lock:
            self.count += 1
            self.total_ns += elapsed_ns
            if elapsed_ns > self.max_ns:
                self.max_ns = elapsed_ns

    @property
    def mean_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0


class MetricManager:
    """The registry (reference: MetricManager.java:36). One process-wide
    instance lives at `janusgraph_tpu.util.metrics`; graphs can also carry
    private managers (per-tx groups use name prefixes instead)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter())
        return c

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.timer(name).update(time.perf_counter_ns() - t0)

    # ------------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, dict]:
        with self._lock:  # stable view while writers insert first-seen names
            counters = dict(self._counters)
            timers = dict(self._timers)
        out: Dict[str, dict] = {}
        for name, c in sorted(counters.items()):
            out[name] = {"type": "counter", "count": c.count}
        for name, t in sorted(timers.items()):
            out[name] = {
                "type": "timer",
                "count": t.count,
                "total_ms": t.total_ns / 1e6,
                "mean_ms": t.mean_ms,
                "max_ms": t.max_ns / 1e6,
            }
        return out

    def report(self) -> str:
        """Console reporter (reference: console reporter config
        GraphDatabaseConfiguration.java:1012)."""
        lines = [f"{'name':50} {'count':>10} {'mean_ms':>10} {'total_ms':>10}"]
        for name, m in self.snapshot().items():
            if m["type"] == "counter":
                lines.append(f"{name:50} {m['count']:>10}")
            else:
                lines.append(
                    f"{name:50} {m['count']:>10} {m['mean_ms']:>10.3f} "
                    f"{m['total_ms']:>10.2f}"
                )
        return "\n".join(lines)

    def get_count(self, name: str) -> int:
        c = self._counters.get(name)
        if c is not None:
            return c.count
        t = self._timers.get(name)
        return t.count if t is not None else 0

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


#: process-wide registry (reference: MetricManager.INSTANCE)
metrics = MetricManager()


class PeriodicReporter:
    """Background reporter thread: periodically renders the registry to the
    console or to per-metric CSV files (reference: the reporter plumbing of
    GraphDatabaseConfiguration.java:1012-1094 — console/CSV reporters with
    a fixed interval). Started from graph open when
    metrics.console-interval-ms / metrics.csv-interval-ms are set."""

    def __init__(
        self,
        manager: MetricManager,
        interval_ms: float,
        mode: str = "console",
        directory: str = "",
        prefix: str = "janusgraph",
        sink=None,
    ):
        if mode not in ("console", "csv"):
            raise ValueError(f"unknown reporter mode {mode!r}")
        if mode == "csv" and not directory:
            raise ValueError("csv reporter requires metrics.csv-directory")
        self.manager = manager
        self.interval_s = interval_ms / 1000.0
        self.mode = mode
        self.directory = directory
        self.prefix = prefix
        self._sink = sink if sink is not None else print
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> "PeriodicReporter":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"metrics-{self.mode}"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 — reporting must not die
                self._sink(f"metrics reporter error: {e}")

    def flush(self) -> None:
        """One reporting tick (also callable directly, e.g. at close)."""
        if self.mode == "console":
            self._sink(
                f"-- metrics [{self.prefix}] @ {time.strftime('%H:%M:%S')}\n"
                + self.manager.report()
            )
            return
        import os
        import re

        os.makedirs(self.directory, exist_ok=True)
        now = time.time()
        for name, m in self.manager.snapshot().items():
            # metric names embed caller-supplied group strings: flatten
            # anything path-like so files cannot escape csv-directory
            safe = re.sub(r"[^\w.\-]", "_", f"{self.prefix}.{name}")
            path = os.path.join(self.directory, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a") as f:
                if m["type"] == "counter":
                    if new:
                        f.write("t,count\n")
                    f.write(f"{now:.3f},{m['count']}\n")
                else:
                    if new:
                        f.write("t,count,mean_ms,total_ms,max_ms\n")
                    f.write(
                        f"{now:.3f},{m['count']},{m['mean_ms']:.3f},"
                        f"{m['total_ms']:.2f},{m['max_ms']:.3f}\n"
                    )

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_flush:
            self.flush()


class MetricInstrumentedStore(KeyColumnValueStore):
    """Times + counts every store operation (reference:
    MetricInstrumentedStore.java — M_GET_SLICE/M_MUTATE/... around each
    call). Metric names: `<prefix>.<store>.<op>`."""

    def __init__(
        self,
        store: KeyColumnValueStore,
        manager: Optional[MetricManager] = None,
        prefix: str = "storage",
        merge_stores: bool = False,
    ):
        self._store = store
        self._m = manager if manager is not None else metrics
        # metrics.merge-stores: one "stores" bucket instead of per-store
        # names (reference: MERGE_BASIC_METRICS / generateName)
        bucket = "stores" if merge_stores else store.name
        self._prefix = f"{prefix}.{bucket}"

    @property
    def name(self) -> str:
        return self._store.name

    @property
    def wrapped(self) -> KeyColumnValueStore:
        return self._store

    def _timed(self, op: str):
        return self._m.time(f"{self._prefix}.{op}")

    def get_slice(self, query, txh: StoreTransaction):
        with self._timed("getSlice"):
            return self._store.get_slice(query, txh)

    def get_slice_multi(self, keys, query, txh: StoreTransaction):
        with self._timed("getSliceMulti"):
            return self._store.get_slice_multi(keys, query, txh)

    def mutate(self, key, additions, deletions, txh: StoreTransaction):
        with self._timed("mutate"):
            return self._store.mutate(key, additions, deletions, txh)

    def acquire_lock(self, key, column, expected, txh: StoreTransaction):
        with self._timed("acquireLock"):
            return self._store.acquire_lock(key, column, expected, txh)

    def get_keys(self, query, txh: StoreTransaction):
        # time only the store's own fetch work (per-next), not the consumer's
        # per-row processing; one timer update per scan, recorded even if the
        # consumer abandons the iterator
        name = f"{self._prefix}.getKeys"
        total = 0
        it = self._store.get_keys(query, txh)
        try:
            while True:
                t0 = time.perf_counter_ns()
                try:
                    item = next(it)
                except StopIteration:
                    total += time.perf_counter_ns() - t0
                    return
                total += time.perf_counter_ns() - t0
                yield item
        finally:
            self._m.timer(name).update(total)

    def close(self) -> None:
        self._store.close()
