"""Operation metrics: the registry facade + the instrumented-store wrapper.

Capability parity with the reference's metrics layer
(reference: util/stats/MetricManager.java:36 — Dropwizard registry
singleton; diskstorage/util/MetricInstrumentedStore.java — per-store
counter+timer around every KCVS call, wrapped at Backend.java:184-188;
per-tx metric groups StandardJanusGraphTx.java:258-262; reporters
GraphDatabaseConfiguration.java:1012-1094).

The registry itself now lives in ``janusgraph_tpu/observability/`` (this
module re-exports it, so every historical import keeps working): counters
and nanosecond timers keyed by dotted names — timers carry log-scale
bucket reservoirs, so p50/p95/p99 report uniformly — plus value
histograms and gauges. This module keeps the storage-facing pieces: the
KCVS decorator timing get_slice/get_slice_multi/mutate/get_keys/
acquire_lock (now also emitting ``store.<op>`` spans), and the periodic
console/CSV reporters. Backend wraps raw stores BEFORE the cache layer,
like the reference, so cache hits are visible as the difference between
tx-level and store-level call counts (the property
JanusGraphOperationCountingTest asserts)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from janusgraph_tpu.observability import registry as metrics, span
from janusgraph_tpu.observability.metrics_core import (
    BUCKET_BOUNDS,
    Counter,
    Gauge,
    Histogram,
    TelemetryRegistry,
    Timer,
)
from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    StoreTransaction,
)

#: historical name for the registry class (graphs can still carry private
#: managers; per-tx groups use name prefixes instead)
MetricManager = TelemetryRegistry

__all__ = [
    "BUCKET_BOUNDS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricInstrumentedStore",
    "MetricManager",
    "PeriodicReporter",
    "TelemetryRegistry",
    "Timer",
    "metrics",
]


class PeriodicReporter:
    """Background reporter thread: periodically renders the registry to the
    console or to per-metric CSV files (reference: the reporter plumbing of
    GraphDatabaseConfiguration.java:1012-1094 — console/CSV reporters with
    a fixed interval). Started from graph open when
    metrics.console-interval-ms / metrics.csv-interval-ms are set."""

    def __init__(
        self,
        manager: MetricManager,
        interval_ms: float,
        mode: str = "console",
        directory: str = "",
        prefix: str = "janusgraph",
        sink=None,
    ):
        if mode not in ("console", "csv"):
            raise ValueError(f"unknown reporter mode {mode!r}")
        if mode == "csv" and not directory:
            raise ValueError("csv reporter requires metrics.csv-directory")
        self.manager = manager
        self.interval_s = interval_ms / 1000.0
        self.mode = mode
        self.directory = directory
        self.prefix = prefix
        self._sink = sink if sink is not None else print
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> "PeriodicReporter":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"metrics-{self.mode}"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 — reporting must not die
                self._sink(f"metrics reporter error: {e}")

    def flush(self) -> None:
        """One reporting tick (also callable directly, e.g. at close)."""
        if self.mode == "console":
            self._sink(
                f"-- metrics [{self.prefix}] @ {time.strftime('%H:%M:%S')}\n"
                + self.manager.report()
            )
            return
        import os
        import re

        os.makedirs(self.directory, exist_ok=True)
        now = time.time()
        for name, m in self.manager.snapshot().items():
            # metric names embed caller-supplied group strings: flatten
            # anything path-like so files cannot escape csv-directory
            safe = re.sub(r"[^\w.\-]", "_", f"{self.prefix}.{name}")
            path = os.path.join(self.directory, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a") as f:
                if m["type"] == "counter":
                    if new:
                        f.write("t,count\n")
                    f.write(f"{now:.3f},{m['count']}\n")
                elif m["type"] == "gauge":
                    if new:
                        f.write("t,value\n")
                    f.write(f"{now:.3f},{m['value']:.6g}\n")
                elif m["type"] == "histogram":
                    if new:
                        f.write("t,count,sum,p50,p95,p99,max\n")
                    f.write(
                        f"{now:.3f},{m['count']},{m['sum']:.6g},"
                        f"{m['p50']:.6g},{m['p95']:.6g},{m['p99']:.6g},"
                        f"{m['max']:.6g}\n"
                    )
                else:
                    if new:
                        f.write(
                            "t,count,mean_ms,total_ms,max_ms,"
                            "p50_ms,p95_ms,p99_ms\n"
                        )
                    f.write(
                        f"{now:.3f},{m['count']},{m['mean_ms']:.3f},"
                        f"{m['total_ms']:.2f},{m['max_ms']:.3f},"
                        f"{m['p50_ms']:.3f},{m['p95_ms']:.3f},"
                        f"{m['p99_ms']:.3f}\n"
                    )

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_flush:
            self.flush()


class MetricInstrumentedStore(KeyColumnValueStore):
    """Times + counts every store operation (reference:
    MetricInstrumentedStore.java — M_GET_SLICE/M_MUTATE/... around each
    call). Metric names: `<prefix>.<store>.<op>` — now histogram-backed
    timers (p50/p95/p99) — and each call runs inside a `store.<op>` span
    so storage work nests under whatever tx/traversal/scan span is
    current."""

    def __init__(
        self,
        store: KeyColumnValueStore,
        manager: Optional[MetricManager] = None,
        prefix: str = "storage",
        merge_stores: bool = False,
    ):
        self._store = store
        self._m = manager if manager is not None else metrics
        # metrics.merge-stores: one "stores" bucket instead of per-store
        # names (reference: MERGE_BASIC_METRICS / generateName)
        bucket = "stores" if merge_stores else store.name
        self._prefix = f"{prefix}.{bucket}"

    @property
    def name(self) -> str:
        return self._store.name

    @property
    def wrapped(self) -> KeyColumnValueStore:
        return self._store

    def _timed(self, op: str):
        return self._m.time(f"{self._prefix}.{op}")

    def get_slice(self, query, txh: StoreTransaction):
        with span("store.getSlice", store=self._store.name):
            with self._timed("getSlice"):
                return self._store.get_slice(query, txh)

    def get_slice_multi(self, keys, query, txh: StoreTransaction):
        with span("store.getSliceMulti", store=self._store.name,
                  keys=len(keys)):
            with self._timed("getSliceMulti"):
                return self._store.get_slice_multi(keys, query, txh)

    def mutate(self, key, additions, deletions, txh: StoreTransaction):
        with span("store.mutate", store=self._store.name,
                  additions=len(additions), deletions=len(deletions)):
            with self._timed("mutate"):
                return self._store.mutate(key, additions, deletions, txh)

    def acquire_lock(self, key, column, expected, txh: StoreTransaction):
        with span("store.acquireLock", store=self._store.name):
            with self._timed("acquireLock"):
                return self._store.acquire_lock(key, column, expected, txh)

    def get_keys(self, query, txh: StoreTransaction):
        # time only the store's own fetch work (per-next), not the consumer's
        # per-row processing; one timer update per scan, recorded even if the
        # consumer abandons the iterator
        name = f"{self._prefix}.getKeys"
        total = 0
        it = self._store.get_keys(query, txh)
        try:
            while True:
                t0 = time.perf_counter_ns()
                try:
                    item = next(it)
                except StopIteration:
                    total += time.perf_counter_ns() - t0
                    return
                total += time.perf_counter_ns() - t0
                yield item
        finally:
            self._m.timer(name).update(total)

    def close(self) -> None:
        self._store.close()
