"""Cluster timestamp providers.

Capability parity with the reference's TimestampProviders
(reference: diskstorage/util/time/TimestampProviders.java — the
`graph.timestamps` config value choosing the resolution every instance
stamps storage-visible times with; serialized into global config, which is
why it is a registered attribute-serializer enum,
StandardSerializer.java:78-132).

All providers return integer NANOSECONDS truncated to their resolution, so
consumers compare/sort timestamps without unit bookkeeping; the resolution
choice governs how coarsely concurrent writers collide (a MILLI cluster
cannot order two same-millisecond log appends by time alone — the log's
(sender, seq) column tail breaks such ties, like the reference's rid).
"""

from __future__ import annotations

import time
from enum import Enum


class TimestampProviders(Enum):
    NANO = 1
    MICRO = 1_000
    MILLI = 1_000_000

    @property
    def resolution_ns(self) -> int:
        return self.value

    def time_ns(self) -> int:
        """Current time, truncated to this provider's resolution."""
        return (time.time_ns() // self.value) * self.value

    @classmethod
    def of(cls, name: str) -> "TimestampProviders":
        try:
            return cls[name.upper()]
        except KeyError:
            raise ValueError(
                f"unknown timestamp provider {name!r} "
                f"(one of {[m.name.lower() for m in cls]})"
            )
