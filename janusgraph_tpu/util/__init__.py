from janusgraph_tpu.util.metrics import (
    MetricInstrumentedStore,
    MetricManager,
    metrics,
)

__all__ = ["MetricInstrumentedStore", "MetricManager", "metrics"]
