"""janusgraph_tpu — a TPU-native property-graph framework.

A brand-new framework with the capability envelope of JanusGraph (the
reference distributed transactional property-graph database): schema-full
property graphs, OLTP traversals, composite/mixed indexing, ACID-ish
transactions with WAL, pluggable sorted-wide-row storage — and, first-class,
an OLAP bulk-synchronous vertex-program engine executed on TPU via JAX:
adjacency bulk-loaded into HBM as CSR blocks, supersteps compiled with
jit/shard_map, cross-partition messages via ICI collectives, global
aggregators via psum.

Architecture is TPU-idiomatic, not a translation of the reference's Java
design. See SURVEY.md for the structural analysis driving capability parity.
"""

__version__ = "0.1.0"


def open_graph(config=None):
    """Open a graph (JanusGraphFactory.open equivalent). Lazy import keeps
    `import janusgraph_tpu` cheap for storage-only users."""
    from janusgraph_tpu.core.graph import open_graph as _open

    return _open(config)


def drop_graph(graph):
    """Destroy a graph's storage and close it (JanusGraphFactory.drop
    equivalent). Irreversible."""
    from janusgraph_tpu.core.graph import drop_graph as _drop

    return _drop(graph)


def export_graphson(graph, path_or_file):
    """Export a graph to line-delimited GraphSON (TinkerPop io() analogue)."""
    from janusgraph_tpu.core.io import export_graphson as _exp

    return _exp(graph, path_or_file)


def import_graphson(graph, path_or_file, batch_size=1000):
    """Import a line-delimited GraphSON export (ids remapped)."""
    from janusgraph_tpu.core.io import import_graphson as _imp

    return _imp(graph, path_or_file, batch_size=batch_size)
