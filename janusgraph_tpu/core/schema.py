"""Schema model: property keys, edge labels, vertex labels — stored as
vertices in the graph itself.

Capability parity with the reference's type system
(reference: graphdb/types/ — schema elements are vertices with system
properties holding a TypeDefinitionMap; types/system/BaseKey.java system
types with fixed ids; database/cache/StandardSchemaCache.java:206 name->id
and id->definition caching).

A schema vertex's row holds:
  EXISTS          system property  (True)
  SCHEMA_NAME     system property  (the type name)
  SCHEMA_DEF      system property  (JSON-encoded definition map)
and the name->id mapping lives in the `graphindex` store under the system
schema-name index so lookups are one slice read.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from threading import RLock
from typing import Dict, Optional, Tuple

from janusgraph_tpu.core.attributes import Serializer
from janusgraph_tpu.core.predicates import Geoshape
from janusgraph_tpu.core.codecs import (
    Cardinality,
    Consistency,
    Multiplicity,
    TypeInfo,
)
from janusgraph_tpu.core.ids import IDManager, VertexIDType
from janusgraph_tpu.exceptions import SchemaViolationError


class SystemTypes:
    """Fixed-id system schema types (reference: types/system/BaseKey.java,
    BaseLabel.java, SystemTypeManager.java). IDs are stable constants —
    they appear in storage cells."""

    def __init__(self, idm: IDManager):
        mk = idm.make_schema_id
        self.EXISTS = mk(VertexIDType.SYSTEM_PROPERTY_KEY, 1)
        self.SCHEMA_NAME = mk(VertexIDType.SYSTEM_PROPERTY_KEY, 2)
        self.SCHEMA_DEF = mk(VertexIDType.SYSTEM_PROPERTY_KEY, 3)
        self.VERTEX_LABEL_EDGE = mk(VertexIDType.SYSTEM_EDGE_LABEL, 1)
        self._infos = {
            self.EXISTS: TypeInfo(self.EXISTS, False),
            self.SCHEMA_NAME: TypeInfo(self.SCHEMA_NAME, False),
            self.SCHEMA_DEF: TypeInfo(self.SCHEMA_DEF, False),
            self.VERTEX_LABEL_EDGE: TypeInfo(self.VERTEX_LABEL_EDGE, True),
        }

    def type_info(self, type_id: int) -> Optional[TypeInfo]:
        return self._infos.get(type_id)


def _attribute_types() -> Dict[str, type]:
    """Schema-declarable property datatypes (reference: the ~60 datatype
    registrations at StandardSerializer.java:78-132; names are the stable
    schema-definition vocabulary persisted in schema cells)."""
    import uuid as _uuid
    from datetime import date as _d, datetime as _dt, time as _t, timedelta

    from decimal import Decimal as _Decimal

    import numpy as np

    from janusgraph_tpu.core.attributes import BigInt as _BigInt, Char, Instant

    return {
        "Boolean": bool,
        "Long": int,
        "Double": float,
        "String": str,
        "Bytes": bytes,
        "Geoshape": Geoshape,
        "FloatList": list,
        "Date": _dt,
        "UUID": _uuid.UUID,
        "Byte": np.int8,
        "Short": np.int16,
        "Int": np.int32,
        "Long64": np.int64,
        "Float": np.float32,
        "Char": Char,
        "Instant": Instant,
        "Duration": timedelta,
        "LocalDate": _d,
        "LocalTime": _t,
        "Array": np.ndarray,
        "BigInteger": _BigInt,
        "Decimal": _Decimal,
    }


_DATA_TYPES: Dict[str, type] = _attribute_types()
_DATA_TYPE_NAMES = {v: k for k, v in _DATA_TYPES.items()}


@dataclass(frozen=True)
class PropertyKey:
    """A property key definition (reference: core/PropertyKey.java)."""

    id: int
    name: str
    data_type: type
    cardinality: Cardinality = Cardinality.SINGLE
    consistency: Consistency = Consistency.DEFAULT
    #: seconds until cells of this type expire (0 = never); requires a
    #: cell-TTL backend (reference: ManagementSystem.setTTL)
    ttl_seconds: int = 0

    @property
    def is_property_key(self) -> bool:
        return True

    @property
    def is_edge_label(self) -> bool:
        return False

    def definition(self) -> dict:
        return {
            "kind": "property",
            "dataType": _DATA_TYPE_NAMES[self.data_type],
            "cardinality": int(self.cardinality),
            "consistency": int(self.consistency),
            "ttl": self.ttl_seconds,
        }

    def type_info(self) -> TypeInfo:
        return TypeInfo(self.id, False, self.cardinality)


@dataclass(frozen=True)
class EdgeLabel:
    """An edge label definition (reference: core/EdgeLabel.java)."""

    id: int
    name: str
    multiplicity: Multiplicity = Multiplicity.MULTI
    # property-key ids whose ordered fixed-width encodings form the sort key
    sort_key: Tuple[int, ...] = ()
    unidirected: bool = False
    consistency: Consistency = Consistency.DEFAULT
    ttl_seconds: int = 0
    #: schema constraints (reference: SchemaManager.addProperties /
    #: addConnection) — enforcement is gated by the schema.constraints
    #: option; when enabled, EMPTY tuples mean nothing is declared (all
    #: writes reject in 'none' mode, auto mode declares on first write)
    allowed_property_ids: Tuple[int, ...] = ()
    connections: Tuple[Tuple[int, int], ...] = ()  # (outV label id, inV label id)

    @property
    def is_property_key(self) -> bool:
        return False

    @property
    def is_edge_label(self) -> bool:
        return True

    def definition(self) -> dict:
        d = {
            "kind": "edge",
            "multiplicity": int(self.multiplicity),
            "sortKey": list(self.sort_key),
            "unidirected": self.unidirected,
            "consistency": int(self.consistency),
            "ttl": self.ttl_seconds,
        }
        if self.allowed_property_ids:
            d["allowedProps"] = list(self.allowed_property_ids)
        if self.connections:
            d["connections"] = [list(c) for c in self.connections]
        return d

    def type_info(self) -> TypeInfo:
        return TypeInfo(self.id, True, Cardinality.SINGLE, self.sort_key)


@dataclass(frozen=True)
class VertexLabel:
    """A vertex label (reference: core/VertexLabel.java). `partitioned`
    marks vertex-cut labels whose adjacency is spread over all partitions."""

    id: int
    name: str
    partitioned: bool = False
    static: bool = False
    ttl_seconds: int = 0
    #: schema constraints (reference: SchemaManager.addProperties)
    allowed_property_ids: Tuple[int, ...] = ()

    def definition(self) -> dict:
        d = {
            "kind": "vertexlabel",
            "partitioned": self.partitioned,
            "static": self.static,
            "ttl": self.ttl_seconds,
        }
        if self.allowed_property_ids:
            d["allowedProps"] = list(self.allowed_property_ids)
        return d


@dataclass(frozen=True)
class RelationIndex:
    """A vertex-centric index on one edge label, built AFTER the label
    exists (reference: core/schema/RelationTypeIndex.java via
    mgmt.buildEdgeIndex): edges of the label are additionally written as
    cells under THIS type id with the index's sort key encoded in the
    column, so sort-range slices work without the label itself being
    sort-keyed. Index cells are invisible to untyped edge enumeration."""

    id: int
    name: str
    label_id: int
    #: property-key ids forming the index sort key (fixed-width encodings)
    sort_key: Tuple[int, ...] = ()
    #: Direction value the index covers (int(Direction.BOTH) = both)
    direction: int = 2
    # REGISTERED (written, not yet queryable) -> ENABLED -> DISABLED
    status: str = "REGISTERED"

    @property
    def is_property_key(self) -> bool:
        return False

    @property
    def is_edge_label(self) -> bool:
        return False

    def definition(self) -> dict:
        return {
            "kind": "relindex",
            "label": self.label_id,
            "sortKey": list(self.sort_key),
            "direction": self.direction,
            "status": self.status,
        }

    def type_info(self) -> TypeInfo:
        return TypeInfo(self.id, True, Cardinality.SINGLE, self.sort_key)

    def sort_key_bytes(self, serializer, props) -> Optional[bytes]:
        """Order-preserving index sort-key bytes for an edge's properties,
        or None when a key is missing (such edges are simply not indexed).
        The ONE encoding shared by the write path, the reindex job, and
        the tx overlay filter."""
        parts = []
        for key_id in self.sort_key:
            if not props or key_id not in props:
                return None
            parts.append(serializer.write_ordered(props[key_id]))
        return b"".join(parts)


@dataclass(frozen=True)
class IndexDefinition:
    """A graph index over property keys, optionally label-constrained.
    Composite (exact-match rows in `graphindex`) or mixed (documents in an
    external IndexProvider) — reference: core/schema/JanusGraphIndex.java;
    mixed/composite split graphdb/types/CompositeIndexType +
    MixedIndexType."""

    id: int
    name: str
    key_ids: Tuple[int, ...]
    unique: bool = False
    label_constraint: Optional[str] = None
    # lifecycle (reference core/schema/SchemaStatus.java):
    # INSTALLED -> REGISTERED -> ENABLED -> DISABLED
    status: str = "ENABLED"
    mixed: bool = False
    backing: Optional[str] = None  # index backend shorthand for mixed
    # key_id -> Mapping name (TEXT/STRING/TEXTSTRING), mixed only
    mappings: Tuple[Tuple[int, str], ...] = ()

    def definition(self) -> dict:
        d = {
            "kind": "index",
            "keys": list(self.key_ids),
            "unique": self.unique,
            "label": self.label_constraint,
            "status": self.status,
        }
        if self.mixed:
            d["mixed"] = True
            d["backing"] = self.backing
            d["mappings"] = [list(m) for m in self.mappings]
        return d

    def mapping_for(self, key_id: int) -> str:
        for kid, m in self.mappings:
            if kid == key_id:
                return m
        return "DEFAULT"


def schema_element_from_definition(sid: int, name: str, d: dict):
    kind = d["kind"]
    if kind == "property":
        return PropertyKey(
            sid,
            name,
            _DATA_TYPES[d["dataType"]],
            Cardinality(d["cardinality"]),
            Consistency(d.get("consistency", 0)),
            d.get("ttl", 0),
        )
    if kind == "edge":
        return EdgeLabel(
            sid,
            name,
            Multiplicity(d["multiplicity"]),
            tuple(d.get("sortKey", ())),
            d.get("unidirected", False),
            Consistency(d.get("consistency", 0)),
            d.get("ttl", 0),
            tuple(d.get("allowedProps", ())),
            tuple(tuple(c) for c in d.get("connections", ())),
        )
    if kind == "vertexlabel":
        return VertexLabel(
            sid, name, d.get("partitioned", False), d.get("static", False),
            d.get("ttl", 0),
            tuple(d.get("allowedProps", ())),
        )
    if kind == "relindex":
        return RelationIndex(
            sid,
            name,
            d["label"],
            tuple(d.get("sortKey", ())),
            d.get("direction", 2),
            d.get("status", "REGISTERED"),
        )
    if kind == "index":
        return IndexDefinition(
            sid,
            name,
            tuple(d["keys"]),
            d.get("unique", False),
            d.get("label"),
            d.get("status", "ENABLED"),
            d.get("mixed", False),
            d.get("backing"),
            tuple((int(k), str(m)) for k, m in d.get("mappings", ())),
        )
    raise SchemaViolationError(f"unknown schema kind {kind!r}")


def encode_definition(d: dict) -> bytes:
    return json.dumps(d, sort_keys=True).encode()


def decode_definition(data: bytes) -> dict:
    return json.loads(data.decode())


class SchemaCache:
    """Name->element and id->element cache with explicit invalidation
    (reference: StandardSchemaCache.java:206). Loading is delegated to the
    graph, which reads schema vertices from storage."""

    def __init__(self, loader_by_name, loader_by_id):
        self._by_name: Dict[str, object] = {}
        self._by_id: Dict[int, object] = {}
        self._load_name = loader_by_name
        self._load_id = loader_by_id
        self._lock = RLock()
        # bumped on every invalidation: a load that STARTED before a
        # concurrent invalidation must not repopulate the cache with its
        # (stale) result — same guard the slice cache uses
        self._generation = 0

    def get_by_name(self, name: str):
        with self._lock:
            el = self._by_name.get(name)
            gen = self._generation
        if el is not None:
            return el
        el = self._load_name(name)
        if el is not None:
            with self._lock:
                if self._generation == gen:
                    self._by_name[name] = el
                    self._by_id[el.id] = el
        return el

    def get_by_id(self, sid: int):
        with self._lock:
            el = self._by_id.get(sid)
            gen = self._generation
        if el is not None:
            return el
        el = self._load_id(sid)
        if el is not None:
            with self._lock:
                if self._generation == gen:
                    self._by_id[sid] = el
                    # index names are a separate namespace: never let an
                    # index shadow a relation type of the same name
                    if not isinstance(el, IndexDefinition):
                        self._by_name[el.name] = el
        return el

    def invalidate(self, name: Optional[str] = None) -> None:
        with self._lock:
            self._generation += 1
            if name is None:
                self._by_name.clear()
                self._by_id.clear()
            else:
                el = self._by_name.pop(name, None)
                if el is not None:
                    self._by_id.pop(el.id, None)

    def invalidate_id(self, sid: int) -> None:
        with self._lock:
            self._generation += 1
            el = self._by_id.pop(sid, None)
            if el is not None:
                self._by_name.pop(el.name, None)

    def data_type_for(self, serializer: Serializer, key: "PropertyKey"):
        return serializer.serializer_for_type(key.data_type)
