"""Transaction WAL, change-data-capture feeds, recovery, and the management
broadcast channel — all riding the durable KCVS log bus.

Capability parity with the reference's tx-log framework
(reference: graphdb/database/log/TransactionLogHeader.java:274 — tx log
entry encoding [txid][status][payload]; graphdb/database/log/LogTxStatus.java
— PRECOMMIT/PRIMARY_SUCCESS/SECONDARY_SUCCESS/SECONDARY_FAILURE;
graphdb/log/StandardTransactionLogProcessor.java:90-151 — tail the txlog and
replay missing *secondary* persistence (fixSecondaryFailure:151);
graphdb/log/StandardLogProcessorFramework.java:248 — user CDC feeds with
ChangeProcessor callbacks; graphdb/database/management/ManagementLogger.java
:287 — schema-cache eviction broadcast with instance acknowledgement).

Change-set payload encoding (binary, self-contained so a recovery process
can replay without the originating tx):
  [n:4 BE] then n records:
    edge:     b'E' [flag][out_vid:8][in_vid:8][type_id:8][rel_id:8]
    property: b'P' [flag][vid:8][key_id:8][rel_id:8][len:4][value-enc]
  flag: 0x01 = addition, 0x00 = deletion
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Dict, List, Optional

from janusgraph_tpu.storage.log import KCVSLog, LogMessage, ReadMarker


class LogTxStatus(IntEnum):
    PRECOMMIT = 1
    PRIMARY_SUCCESS = 2
    SECONDARY_SUCCESS = 3
    SECONDARY_FAILURE = 4
    #: written immediately before the primary storage flush — the point
    #: past which a crash can leave a TORN batch (some rows applied, some
    #: not). PREFLUSH without PRIMARY_SUCCESS is the roll-forward case
    #: TornCommitRecovery replays; PRECOMMIT without PREFLUSH means the
    #: flush never started and the tx rolls back to "never happened".
    PREFLUSH = 5
    #: recovery marker: a PRECOMMIT-only tx was confirmed rolled back
    ROLLED_BACK = 6


@dataclass(frozen=True)
class ChangeRecord:
    kind: str  # 'edge' | 'property'
    added: bool
    vertex_id: int  # out-vertex for edges
    other_id: int  # in-vertex for edges, 0 for properties
    type_id: int
    relation_id: int
    value_enc: bytes = b""


@dataclass
class TxLogEntry:
    tx_id: int
    status: LogTxStatus
    changes: List[ChangeRecord] = field(default_factory=list)
    user_log: str = ""
    timestamp_ns: int = 0


def encode_changes(changes: List[ChangeRecord]) -> bytes:
    parts = [struct.pack(">I", len(changes))]
    for c in changes:
        flag = b"\x01" if c.added else b"\x00"
        if c.kind == "edge":
            parts.append(
                b"E" + flag + struct.pack(
                    ">QQQQ", c.vertex_id, c.other_id, c.type_id, c.relation_id
                )
            )
        else:
            parts.append(
                b"P" + flag + struct.pack(
                    ">QQQ", c.vertex_id, c.type_id, c.relation_id
                )
                + struct.pack(">I", len(c.value_enc)) + c.value_enc
            )
    return b"".join(parts)


def decode_changes(data: bytes) -> List[ChangeRecord]:
    (n,) = struct.unpack_from(">I", data, 0)
    off = 4
    out: List[ChangeRecord] = []
    for _ in range(n):
        kind = data[off:off + 1]
        added = data[off + 1] == 1
        off += 2
        if kind == b"E":
            ov, iv, tid, rid = struct.unpack_from(">QQQQ", data, off)
            off += 32
            out.append(ChangeRecord("edge", added, ov, iv, tid, rid))
        else:
            vid, tid, rid = struct.unpack_from(">QQQ", data, off)
            off += 24
            (vlen,) = struct.unpack_from(">I", data, off)
            off += 4
            venc = data[off:off + vlen]
            off += vlen
            out.append(ChangeRecord("property", added, vid, 0, tid, rid, venc))
    return out


def encode_tx_entry(entry: TxLogEntry) -> bytes:
    ulog = entry.user_log.encode()
    head = struct.pack(">QBH", entry.tx_id, entry.status, len(ulog)) + ulog
    if entry.status == LogTxStatus.PRECOMMIT:
        return head + encode_changes(entry.changes)
    return head


def decode_tx_entry(data: bytes, timestamp_ns: int = 0) -> TxLogEntry:
    tx_id, status, ulen = struct.unpack_from(">QBH", data, 0)
    off = 11
    ulog = data[off:off + ulen].decode()
    off += ulen
    changes: List[ChangeRecord] = []
    if status == LogTxStatus.PRECOMMIT:
        changes = decode_changes(data[off:])
    return TxLogEntry(tx_id, LogTxStatus(status), changes, ulog, timestamp_ns)


# ---------------------------------------------------------------------------
# WAL writer used by the commit pipeline


class TransactionLog:
    def __init__(self, txlog: KCVSLog):
        self.log = txlog
        self._tx_counter = int(time.time_ns() & 0x7FFFFFFF) << 20
        self._lock = threading.Lock()

    def next_tx_id(self) -> int:
        with self._lock:
            self._tx_counter += 1
            return self._tx_counter

    def precommit(
        self, tx_id: int, changes: List[ChangeRecord], user_log: str = ""
    ) -> None:
        self.log.add_now(
            encode_tx_entry(
                TxLogEntry(tx_id, LogTxStatus.PRECOMMIT, changes, user_log)
            )
        )

    def preflush(self, tx_id: int) -> None:
        """Mark the flush point: storage writes begin NOW. A crash between
        this entry and primary_success may have torn the batch."""
        self.log.add_now(
            encode_tx_entry(TxLogEntry(tx_id, LogTxStatus.PREFLUSH))
        )

    def primary_success(self, tx_id: int) -> None:
        self.log.add_now(
            encode_tx_entry(TxLogEntry(tx_id, LogTxStatus.PRIMARY_SUCCESS))
        )

    def secondary(self, tx_id: int, success: bool) -> None:
        status = (
            LogTxStatus.SECONDARY_SUCCESS
            if success
            else LogTxStatus.SECONDARY_FAILURE
        )
        self.log.add_now(encode_tx_entry(TxLogEntry(tx_id, status)))


# ---------------------------------------------------------------------------
# User CDC


@dataclass
class ChangeState:
    """What one committed transaction changed, reconstructed from the log
    (reference: core/log/ChangeState over the user log)."""

    tx_id: int
    timestamp_ns: int
    added: List[ChangeRecord]
    deleted: List[ChangeRecord]


class LogProcessorFramework:
    """Tail a user change log and dispatch ChangeState callbacks
    (reference: StandardLogProcessorFramework.java:248)."""

    def __init__(self, graph, identifier: str):
        self.graph = graph
        self.identifier = identifier
        self._processors: List[Callable[[ChangeState], None]] = []
        self._started = False

    def add_processor(self, fn: Callable[[ChangeState], None]) -> "LogProcessorFramework":
        self._processors.append(fn)
        return self

    def build(self, marker: Optional[ReadMarker] = None) -> "LogProcessorFramework":
        log = self.graph.log_manager.open_log("ulog_" + self.identifier)
        log.register_reader(marker or ReadMarker.from_now(), self._on_message)
        self._started = True
        return self

    def _on_message(self, msg: LogMessage) -> None:
        entry = decode_tx_entry(msg.content, msg.timestamp_ns)
        state = ChangeState(
            entry.tx_id,
            msg.timestamp_ns,
            [c for c in entry.changes if c.added],
            [c for c in entry.changes if not c.added],
        )
        for fn in self._processors:
            fn(state)


# ---------------------------------------------------------------------------
# Recovery


class TransactionRecovery:
    """Scan the txlog and heal transactions whose *secondary* persistence
    (user-log delivery, mixed-index documents) never completed. Primary
    storage is the source of truth: a tx without PRIMARY_SUCCESS simply never
    happened and is skipped (reference:
    StandardTransactionLogProcessor.fixSecondaryFailure:151, standalone
    process started by JanusGraphFactory.startTransactionRecovery)."""

    def __init__(self, graph, start_ns: int = 0):
        self.graph = graph
        self.start_ns = start_ns
        self.healed: List[int] = []

    def run(self, max_commit_time_ms: Optional[float] = None) -> List[int]:
        if max_commit_time_ms is None:
            max_commit_time_ms = self.graph.config.get("tx.max-commit-time-ms")
        txlog = self.graph.log_manager.open_log("txlog")
        cutoff = time.time_ns() - int(max_commit_time_ms * 1e6)
        # tx ids are only unique per writing instance — key by (sender, txid)
        by_tx: Dict[tuple, Dict[LogTxStatus, TxLogEntry]] = {}
        healed_keys = set()
        for msg in txlog.read_range(self.start_ns):
            entry = decode_tx_entry(msg.content, msg.timestamp_ns)
            if entry.status == LogTxStatus.SECONDARY_SUCCESS and entry.user_log.startswith("healed:"):
                # marker written by a recovery process on behalf of the
                # original sender (so idempotence survives sender-keying)
                healed_keys.add(
                    (bytes.fromhex(entry.user_log[7:]), entry.tx_id)
                )
                continue
            by_tx.setdefault((msg.sender, entry.tx_id), {})[entry.status] = entry
        for (sender, tx_id), entries in sorted(by_tx.items()):
            pre = entries.get(LogTxStatus.PRECOMMIT)
            if pre is None or LogTxStatus.PRIMARY_SUCCESS not in entries:
                continue  # primary never landed: nothing to heal
            if LogTxStatus.SECONDARY_SUCCESS in entries:
                continue
            if (sender, tx_id) in healed_keys:
                continue
            newest = max(e.timestamp_ns for e in entries.values())
            if newest > cutoff:
                continue  # may still be in flight
            self._fix_secondary(sender, tx_id, pre)
            self.healed.append(tx_id)
        return self.healed

    def _fix_secondary(self, sender: bytes, tx_id: int, pre: TxLogEntry) -> None:
        graph = self.graph
        # replay the user-log delivery
        if pre.user_log:
            ulog = graph.log_manager.open_log("ulog_" + pre.user_log)
            ulog.add_now(
                encode_tx_entry(
                    TxLogEntry(
                        tx_id, LogTxStatus.PRECOMMIT, pre.changes, pre.user_log
                    )
                )
            )
        # replay mixed-index documents from primary storage
        graph.restore_mixed_indexes(pre.changes)
        graph.tx_log.log.add_now(
            encode_tx_entry(
                TxLogEntry(
                    tx_id,
                    LogTxStatus.SECONDARY_SUCCESS,
                    user_log="healed:" + sender.hex(),
                )
            )
        )


# ---------------------------------------------------------------------------
# Torn-commit recovery (primary storage)


class TornCommitRecovery:
    """Heal transactions whose PRIMARY flush may have torn.

    The companion to :class:`TransactionRecovery` (which only heals
    *secondary* persistence): this one repairs primary storage itself,
    using the PREFLUSH marker to split abandoned transactions into two
    cases —

    * ``PREFLUSH`` present, ``PRIMARY_SUCCESS`` absent: the flush started
      and may have applied a prefix of the batch (non-transactional
      backends apply per-row atomically, never per-batch). The WAL's
      change records are self-contained, so the tx is **rolled forward**:
      every recorded cell is re-derived and written idempotently
      (``graph.replay_torn_changes``), then a ``PRIMARY_SUCCESS`` entry
      with a ``healed-primary:<sender>`` marker closes the tx.
    * ``PRECOMMIT`` only: the flush never began, nothing reached storage —
      the tx **rolls back** to "never happened" and a ``ROLLED_BACK``
      marker stops future recoveries from re-reporting it.

    Entries younger than ``tx.max-commit-time-ms`` are skipped (they may
    still be in flight on another instance). Runs automatically at graph
    open when the WAL is enabled (``tx.recover-on-open``).
    """

    def __init__(self, graph):
        self.graph = graph
        self.replayed: List[int] = []
        self.rolled_back: List[int] = []

    def run(self, max_commit_time_ms: Optional[float] = None) -> dict:
        if max_commit_time_ms is None:
            max_commit_time_ms = self.graph.config.get("tx.max-commit-time-ms")
        txlog = self.graph.log_manager.open_log("txlog")
        cutoff = time.time_ns() - int(max_commit_time_ms * 1e6)
        by_tx: Dict[tuple, Dict[LogTxStatus, TxLogEntry]] = {}
        handled = set()
        for msg in txlog.read_range(0):
            entry = decode_tx_entry(msg.content, msg.timestamp_ns)
            marker = entry.user_log
            if entry.status == LogTxStatus.PRIMARY_SUCCESS and (
                marker.startswith("healed-primary:")
            ):
                handled.add((bytes.fromhex(marker[15:]), entry.tx_id))
                continue
            if entry.status == LogTxStatus.ROLLED_BACK:
                if marker.startswith("rolledback:"):
                    handled.add((bytes.fromhex(marker[11:]), entry.tx_id))
                continue
            by_tx.setdefault((msg.sender, entry.tx_id), {})[entry.status] = entry
        for (sender, tx_id), entries in sorted(by_tx.items()):
            pre = entries.get(LogTxStatus.PRECOMMIT)
            if pre is None or LogTxStatus.PRIMARY_SUCCESS in entries:
                continue  # unknown origin, or committed cleanly
            if (sender, tx_id) in handled:
                continue
            newest = max(e.timestamp_ns for e in entries.values())
            if newest > cutoff:
                continue  # may still be in flight
            if LogTxStatus.PREFLUSH in entries:
                self._roll_forward(sender, tx_id, pre)
            else:
                self._roll_back(sender, tx_id)
        from janusgraph_tpu.observability import registry

        if self.replayed:
            registry.counter("txlog.torn.replayed").inc(len(self.replayed))
        if self.rolled_back:
            registry.counter("txlog.torn.rolled_back").inc(
                len(self.rolled_back)
            )
        return {"replayed": self.replayed, "rolled_back": self.rolled_back}

    @staticmethod
    def _flight(action: str, tx_id: int, **detail) -> None:
        from janusgraph_tpu.observability import flight_recorder, get_logger

        flight_recorder.record(
            "torn_recovery", action=action, tx_id=tx_id, **detail
        )
        get_logger("core.txlog").warning(
            "torn-recovery", action=action, tx_id=tx_id, **detail
        )

    def _roll_forward(self, sender: bytes, tx_id: int, pre: TxLogEntry) -> None:
        graph = self.graph
        self._flight("replayed", tx_id, changes=len(pre.changes))
        graph.replay_torn_changes(pre.changes)
        # secondary persistence of the healed tx: mixed-index documents are
        # re-derived from (now repaired) primary storage, and the user-log
        # delivery replays — same healing the secondary recovery applies
        graph.restore_mixed_indexes(pre.changes)
        if pre.user_log:
            ulog = graph.log_manager.open_log("ulog_" + pre.user_log)
            ulog.add_now(
                encode_tx_entry(
                    TxLogEntry(
                        tx_id, LogTxStatus.PRECOMMIT, pre.changes, pre.user_log
                    )
                )
            )
        graph.tx_log.log.add_now(
            encode_tx_entry(
                TxLogEntry(
                    tx_id,
                    LogTxStatus.PRIMARY_SUCCESS,
                    user_log="healed-primary:" + sender.hex(),
                )
            )
        )
        self.replayed.append(tx_id)

    def _roll_back(self, sender: bytes, tx_id: int) -> None:
        self._flight("rolled_back", tx_id)
        # PRECOMMIT without PREFLUSH: nothing reached storage, the tx never
        # happened — record that verdict so later recoveries skip it
        self.graph.tx_log.log.add_now(
            encode_tx_entry(
                TxLogEntry(
                    tx_id,
                    LogTxStatus.ROLLED_BACK,
                    user_log="rolledback:" + sender.hex(),
                )
            )
        )
        self.rolled_back.append(tx_id)


# ---------------------------------------------------------------------------
# Management broadcast (schema-cache eviction with acknowledgement)

_EVICT = b"EV"
_ACK = b"AK"


class ManagementLogger:
    """Broadcast schema evictions on the system log; every instance clears
    its caches and acknowledges (reference: ManagementLogger.java:287 with
    ack-tracking inner classes on the ``systemlog``)."""

    def __init__(self, graph):
        self.graph = graph
        self.log = graph.log_manager.open_log("systemlog")
        self._acks: Dict[int, set] = {}
        self._lock = threading.Lock()
        self.log.register_reader(ReadMarker.from_now(), self._on_message)

    def broadcast_eviction(self, schema_id: int) -> int:
        evict_id = time.time_ns()
        payload = _EVICT + struct.pack(">QQ", evict_id, schema_id)
        with self._lock:
            self._acks[evict_id] = set()
        self.log.add_now(payload)
        return evict_id

    def wait_for_acks(
        self, evict_id: int, expected: int, timeout_s: float = 5.0
    ) -> bool:
        # schema.eviction-ack-poll-ms: ack-check cadence (trade latency of
        # schema-change completion against systemlog read pressure)
        poll_s = self.graph.config.get("schema.eviction-ack-poll-ms") / 1000.0
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if len(self._acks.get(evict_id, ())) >= expected:
                    return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(poll_s, remaining))

    def _on_message(self, msg: LogMessage) -> None:
        tag = msg.content[:2]
        if tag == _EVICT:
            evict_id, schema_id = struct.unpack_from(">QQ", msg.content, 2)
            self.graph.evict_schema_element(schema_id)
            self.log.add_now(
                _ACK
                + struct.pack(">Q", evict_id)
                + self.graph.instance_id.encode()
            )
        elif tag == _ACK:
            (evict_id,) = struct.unpack_from(">Q", msg.content, 2)
            instance = msg.content[10:].decode()
            with self._lock:
                if evict_id in self._acks:
                    self._acks[evict_id].add(instance)
