"""Columnar bulk loader: edge lists -> raw storage cells, batched.

The reference reserves a "batch-loading" mode that skips consistency checks
and retries (reference: GraphDatabaseConfiguration storage.batch-loading;
bulk loading docs docs/operations/bulk-loading.md) but still funnels every
element through per-object transaction machinery. Here bulk ingestion is
columnar end to end: vertex ids come as block spans from the ID authority,
edge cells are rendered as one numpy (m, EDGE_COL_FIXED) byte matrix with
vectorized field fills, and rows flush through the backend's buffered
mutator in chunks. This is the write-side mirror of the scan->CSR bulk
decode (olap/csr.py load_csr).

Consistency contract (same as the reference's batch mode): no multiplicity
checks, no locks, no WAL entries, no index maintenance — use it to seed a
graph, not to mutate a live one. Schema (labels/keys) must exist or be
auto-creatable.
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np

from janusgraph_tpu.core.codecs import Direction, EDGE_COL_FIXED, _category_byte


def _render_edge_cols(
    type_id: int,
    direction: Direction,
    others: np.ndarray,
    rels: np.ndarray,
    idm,
) -> np.ndarray:
    """Vectorized render of fixed-width edge columns: (m, EDGE_COL_FIXED)
    uint8, fields filled via big-endian views (the inverse of
    EdgeSerializer.bulk_decode_edges)."""
    m = len(others)
    buf = np.zeros((m, EDGE_COL_FIXED), dtype=np.uint8)
    buf[:, 0] = _category_byte(type_id, True, idm)
    buf[:, 1:9] = np.frombuffer(
        np.full(m, type_id, dtype=">u8").tobytes(), dtype=np.uint8
    ).reshape(m, 8)
    buf[:, 9] = int(direction)
    # byte 10 = sort-key length = 0
    buf[:, 11:19] = np.frombuffer(
        others.astype(">u8").tobytes(), dtype=np.uint8
    ).reshape(m, 8)
    buf[:, 19:27] = np.frombuffer(
        rels.astype(">u8").tobytes(), dtype=np.uint8
    ).reshape(m, 8)
    return buf


def bulk_add_vertices(
    graph,
    count: int,
    label: Optional[str] = None,
    batch: int = 100_000,
) -> np.ndarray:
    """Create `count` vertices (EXISTS cell + optional label cell each),
    returning their ids as an int64 array."""
    idm = graph.idm
    es = graph.edge_serializer
    st = graph.system_types

    label_el = None
    if label is not None:
        label_el = graph.schema_cache.get_by_name(label)
        if label_el is None:
            label_el = graph.management().make_vertex_label(label)

    # ids: spread over partitions in span-sized stripes
    vids = np.empty(count, dtype=np.int64)
    filled = 0
    parts = idm.num_partitions
    per_part = -(-count // parts)
    for p in range(parts):
        take = min(per_part, count - filled)
        if take <= 0:
            break
        for start, ln in graph.id_assigner._pool(p).next_ids(take):
            counts = np.arange(start, start + ln, dtype=np.int64)
            vids[filled : filled + ln] = (
                ((counts << idm.partition_bits) | p) << 3
            )  # NORMAL suffix 0b000
            filled += ln
    vids = vids[:filled]

    # unique relation ids per cell (the same invariant the tx path keeps —
    # rel-id-keyed deletion filtering and RelationIdentifier equality rely
    # on it): one span-drawn id per EXISTS cell, one per label edge
    per_vertex = 1 if label_el is None else 2
    rels = np.empty(len(vids) * per_vertex, dtype=np.int64)
    off = 0
    for start, ln in graph.id_assigner.assign_relation_ids(len(rels)):
        rels[off : off + ln] = np.arange(start, start + ln, dtype=np.int64)
        off += ln

    # EXISTS value = [rel_id:8][framed True]; only the rel id varies
    exists_col, exists_val_tpl = es.write_property(st.EXISTS, 1, True)
    exists_tail = exists_val_tpl[8:]
    label_col_tpl = (
        es.write_edge(st.VERTEX_LABEL_EDGE, Direction.OUT, label_el.id, 1)[0]
        if label_el is not None
        else None
    )
    keys = idm.get_keys_array(vids)
    for lo in range(0, len(vids), batch):
        btx = graph.backend.begin_transaction()
        for i in range(lo, min(lo + batch, len(vids))):
            rid = int(rels[i * per_vertex])
            adds = [(exists_col, struct.pack(">Q", rid) + exists_tail)]
            if label_col_tpl is not None:
                lrid = int(rels[i * per_vertex + 1])
                # relation id sits in the last 8 bytes of the edge column
                adds.append((label_col_tpl[:-8] + struct.pack(">Q", lrid), b""))
            btx.mutate_edges(keys[i], adds, [])
        btx.commit()
    return vids


def bulk_add_edges(
    graph,
    label: str,
    src_vids: Sequence[int],
    dst_vids: Sequence[int],
    batch: int = 200_000,
) -> int:
    """Write edges columnar: OUT cell on each src row, IN cell on each dst
    row, relation ids from bulk spans. Returns the number of edges written."""
    idm = graph.idm
    el = graph.schema_cache.get_by_name(label)
    if el is None:
        el = graph.management().make_edge_label(label)

    src = np.asarray(src_vids, dtype=np.int64)
    dst = np.asarray(dst_vids, dtype=np.int64)
    if len(src) != len(dst):
        raise ValueError("src/dst length mismatch")
    m = len(src)
    rels = np.empty(m, dtype=np.int64)
    off = 0
    for start, ln in graph.id_assigner.assign_relation_ids(m):
        rels[off : off + ln] = np.arange(start, start + ln, dtype=np.int64)
        off += ln

    out_cols = _render_edge_cols(el.id, Direction.OUT, dst, rels, idm)
    in_cols = _render_edge_cols(el.id, Direction.IN, src, rels, idm)
    src_keys = idm.get_keys_array(src)
    dst_keys = idm.get_keys_array(dst)

    for lo in range(0, m, batch):
        hi = min(lo + batch, m)
        # group cells by row key within the chunk
        per_row: dict = {}
        for i in range(lo, hi):
            per_row.setdefault(src_keys[i], []).append(
                (out_cols[i].tobytes(), b"")
            )
            per_row.setdefault(dst_keys[i], []).append(
                (in_cols[i].tobytes(), b"")
            )
        btx = graph.backend.begin_transaction()
        for key, adds in per_row.items():
            btx.mutate_edges(key, adds, [])
        btx.commit()
    return m
