"""Attribute serializer registry.

Capability parity with the reference's serializer stack
(reference: graphdb/database/serialize/StandardSerializer.java:78-132
fixed-id registrations; serialize/attribute/*): a registry of binary
serializers keyed by a stable small integer id, with an *order-preserving*
mode used for sort keys and composite-index keys (byte-wise lexicographic
order of the encoding == natural order of the value).

Own design notes (not a port): encodings are fixed-width big-endian where
possible so the OLAP bulk loader can decode property columns with vectorized
numpy views instead of per-value Python.
"""

from __future__ import annotations

import struct
import uuid as _uuid
import zlib
from dataclasses import dataclass
from datetime import date as _date, datetime, time as _time, timedelta, timezone
from decimal import Decimal
from enum import Enum
from typing import Any, Dict, Optional, Tuple, Type

import numpy as np

from janusgraph_tpu.exceptions import JanusGraphTPUError


class SerializerError(JanusGraphTPUError):
    pass


class AttributeSerializer:
    """One datatype's binary codec. Subclasses set `type_id` and `py_type`."""

    type_id: int = -1
    py_type: type = object
    #: fixed encoded byte width, or None if variable
    fixed_width: Optional[int] = None

    def write(self, value) -> bytes:
        raise NotImplementedError

    def read(self, data: bytes):
        raise NotImplementedError

    # order-preserving variants default to the plain encoding when the plain
    # encoding already sorts correctly; override otherwise.
    def write_ordered(self, value) -> bytes:
        return self.write(value)

    def read_ordered(self, data: bytes):
        return self.read(data)


class BooleanSerializer(AttributeSerializer):
    type_id = 1
    py_type = bool
    fixed_width = 1

    def write(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def read(self, data: bytes):
        return data[0] != 0


class LongSerializer(AttributeSerializer):
    """Signed 64-bit. Ordered form flips the sign bit so byte order == numeric
    order (two's-complement big-endian sorts negatives after positives
    otherwise)."""

    type_id = 2
    py_type = int
    fixed_width = 8

    def write(self, value) -> bytes:
        return struct.pack(">q", value)

    def read(self, data: bytes):
        return struct.unpack(">q", data)[0]

    def write_ordered(self, value) -> bytes:
        # struct raises on out-of-range, matching the plain write() path
        return struct.pack(">Q", value + (1 << 63))

    def read_ordered(self, data: bytes):
        return struct.unpack(">Q", data)[0] - (1 << 63)


class DoubleSerializer(AttributeSerializer):
    """IEEE-754 double. Ordered form uses the total-order trick: flip all bits
    of negatives, flip only the sign bit of non-negatives."""

    type_id = 3
    py_type = float
    fixed_width = 8

    def write(self, value) -> bytes:
        return struct.pack(">d", value)

    def read(self, data: bytes):
        return struct.unpack(">d", data)[0]

    def write_ordered(self, value) -> bytes:
        bits = struct.unpack(">Q", struct.pack(">d", value))[0]
        if bits & (1 << 63):
            bits ^= (1 << 64) - 1
        else:
            bits ^= 1 << 63
        return struct.pack(">Q", bits)

    def read_ordered(self, data: bytes):
        bits = struct.unpack(">Q", data)[0]
        if bits & (1 << 63):
            bits ^= 1 << 63
        else:
            bits ^= (1 << 64) - 1
        return struct.unpack(">d", struct.pack(">Q", bits))[0]


class StringSerializer(AttributeSerializer):
    """UTF-8 with transparent compression for long values: payload is
    [flag:1][body], flag 0 = raw utf-8, 1 = zlib(utf-8) (reference:
    serialize/attribute/StringSerializer.java:279 compresses long strings
    the same way). Ordered form stays raw + NUL terminator — compression
    would destroy byte ordering; embedded NULs are rejected there so prefix
    containment can't corrupt ordering."""

    type_id = 4
    py_type = str
    COMPRESS_THRESHOLD = 48

    def write(self, value) -> bytes:
        raw = value.encode("utf-8")
        if len(raw) > self.COMPRESS_THRESHOLD:
            z = zlib.compress(raw, 6)
            if len(z) < len(raw):
                return b"\x01" + z
        return b"\x00" + raw

    def read(self, data: bytes):
        if data[:1] == b"\x01":
            return zlib.decompress(data[1:]).decode("utf-8")
        return data[1:].decode("utf-8")

    def write_ordered(self, value) -> bytes:
        raw = value.encode("utf-8")
        if b"\x00" in raw:
            raise SerializerError("NUL not allowed in ordered (sort-key) strings")
        return raw + b"\x00"

    def read_ordered(self, data: bytes):
        if not data.endswith(b"\x00"):
            raise SerializerError("malformed ordered string")
        return data[:-1].decode("utf-8")


class BytesSerializer(AttributeSerializer):
    type_id = 5
    py_type = bytes

    def write(self, value) -> bytes:
        return bytes(value)

    def read(self, data: bytes):
        return bytes(data)


class DateSerializer(AttributeSerializer):
    """UTC datetime as epoch-micros int64 (ordered like LongSerializer)."""

    type_id = 6
    py_type = datetime
    fixed_width = 8

    _EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)

    def _to_micros(self, value: datetime) -> int:
        if value.tzinfo is None:
            value = value.replace(tzinfo=timezone.utc)
        # integer arithmetic: float timestamps lose microseconds far from epoch
        return (value - self._EPOCH) // timedelta(microseconds=1)

    def _from_micros(self, micros: int) -> datetime:
        return self._EPOCH + timedelta(microseconds=micros)

    def write(self, value) -> bytes:
        return struct.pack(">q", self._to_micros(value))

    def read(self, data: bytes):
        return self._from_micros(struct.unpack(">q", data)[0])

    def write_ordered(self, value) -> bytes:
        return LongSerializer().write_ordered(self._to_micros(value))

    def read_ordered(self, data: bytes):
        return self._from_micros(LongSerializer().read_ordered(data))


class UUIDSerializer(AttributeSerializer):
    type_id = 7
    py_type = _uuid.UUID
    fixed_width = 16

    def write(self, value) -> bytes:
        return value.bytes

    def read(self, data: bytes):
        return _uuid.UUID(bytes=bytes(data))


class FloatListSerializer(AttributeSerializer):
    """list[float] — the OLAP compute-property carrier (e.g. pagerank vectors)."""

    type_id = 8
    py_type = list

    def write(self, value) -> bytes:
        return struct.pack(f">{len(value)}d", *value)

    def read(self, data: bytes):
        n = len(data) // 8
        return list(struct.unpack(f">{n}d", data))


from janusgraph_tpu.core.predicates import Geoshape


def GeoshapePoint(lat: float, lon: float) -> Geoshape:
    """Compat shim: the original minimal point type, now the full Geoshape
    vocabulary (reference: core/attribute/Geoshape.java:623)."""
    return Geoshape.point(lat, lon)


class GeoshapeSerializer(AttributeSerializer):
    """Kind-tagged binary: 0x01 point[2d], 0x02 circle[3d], 0x03 box[4d],
    0x04 polygon[count:2][2d each], 0x05 line, 0x06 multipoint (point
    lists), 0x07/0x08/0x09 multilinestring/multipolygon/collection
    ([count:2] nested length-prefixed sub-shapes) — the full Geoshape
    vocabulary (reference: Geoshape.GeoShapeSerializer binary codec,
    attribute/Geoshape.java:623)."""

    type_id = 9
    py_type = Geoshape

    _PART_TAGS = {
        "MultiLineString": b"\x07",
        "MultiPolygon": b"\x08",
        "GeometryCollection": b"\x09",
    }

    def write(self, value) -> bytes:
        if value.kind == "Point":
            return b"\x01" + struct.pack(">dd", value.lat, value.lon)
        if value.kind == "Circle":
            return b"\x02" + struct.pack(
                ">ddd", value.lat, value.lon, value.radius_km
            )
        if value.kind == "Box":
            (slat, slon), (nlat, nlon) = value.coords
            return b"\x03" + struct.pack(">dddd", slat, slon, nlat, nlon)
        tag = self._PART_TAGS.get(value.kind)
        if tag is not None:
            if len(value.parts) > 0xFFFF:
                raise SerializerError(
                    f"{value.kind} exceeds 65535 parts ({len(value.parts)})"
                )
            out = [tag, struct.pack(">H", len(value.parts))]
            for p in value.parts:
                sub = self.write(p)
                out.append(struct.pack(">I", len(sub)))
                out.append(sub)
            return b"".join(out)
        tag = {"Polygon": b"\x04", "Line": b"\x05", "MultiPoint": b"\x06"}[
            value.kind
        ]
        if len(value.coords) > 0xFFFF:
            raise SerializerError(
                f"{value.kind} exceeds 65535 points ({len(value.coords)})"
            )
        out = [tag, struct.pack(">H", len(value.coords))]
        for la, lo in value.coords:
            out.append(struct.pack(">dd", la, lo))
        return b"".join(out)

    def read(self, data: bytes):
        kind = data[0]
        if kind == 1:
            return Geoshape.point(*struct.unpack(">dd", data[1:17]))
        if kind == 2:
            return Geoshape.circle(*struct.unpack(">ddd", data[1:25]))
        if kind == 3:
            return Geoshape.box(*struct.unpack(">dddd", data[1:33]))
        if kind in (7, 8, 9):
            (n,) = struct.unpack(">H", data[1:3])
            off = 3
            parts = []
            for _ in range(n):
                (ln,) = struct.unpack(">I", data[off:off + 4])
                off += 4
                parts.append(self.read(data[off:off + ln]))
                off += ln
            if kind == 7:
                return Geoshape.multilinestring(parts)
            if kind == 8:
                return Geoshape.multipolygon(parts)
            return Geoshape.geometry_collection(parts)
        (n,) = struct.unpack(">H", data[1:3])
        pts = [
            struct.unpack(">dd", data[3 + 16 * i : 19 + 16 * i]) for i in range(n)
        ]
        if kind == 5:
            return Geoshape.line(pts)
        if kind == 6:
            return Geoshape.multipoint(pts)
        return Geoshape.polygon(pts)


# --------------------------------------------------------------------------
# Sized integer / float scalars (reference registers Java's Byte/Short/
# Integer/Float as distinct datatypes, StandardSerializer.java:78-132; the
# TPU-idiomatic Python carriers are the numpy sized scalar types, which is
# also what OLAP property arrays decode to)
# --------------------------------------------------------------------------

class _SizedIntSerializer(AttributeSerializer):
    fmt = ">q"
    bias = 1 << 63

    def write(self, value) -> bytes:
        return struct.pack(self.fmt, int(value))

    def read(self, data: bytes):
        return self.py_type(struct.unpack(self.fmt, data)[0])

    def write_ordered(self, value) -> bytes:
        # sign-bias so byte-lexicographic order == numeric order
        return struct.pack(self.fmt.upper(), int(value) + self.bias)

    def read_ordered(self, data: bytes):
        return self.py_type(struct.unpack(self.fmt.upper(), data)[0] - self.bias)


class ByteSerializer(_SizedIntSerializer):
    type_id = 10
    py_type = np.int8
    fixed_width = 1
    fmt = ">b"
    bias = 1 << 7


class ShortSerializer(_SizedIntSerializer):
    type_id = 11
    py_type = np.int16
    fixed_width = 2
    fmt = ">h"
    bias = 1 << 15


class IntSerializer(_SizedIntSerializer):
    type_id = 12
    py_type = np.int32
    fixed_width = 4
    fmt = ">i"
    bias = 1 << 31


class NumpyLongSerializer(_SizedIntSerializer):
    type_id = 13
    py_type = np.int64
    fixed_width = 8
    fmt = ">q"
    bias = 1 << 63


class FloatSerializer(AttributeSerializer):
    """IEEE-754 single; same total-order trick as DoubleSerializer."""

    type_id = 14
    py_type = np.float32
    fixed_width = 4

    def write(self, value) -> bytes:
        return struct.pack(">f", float(value))

    def read(self, data: bytes):
        return np.float32(struct.unpack(">f", data)[0])

    def write_ordered(self, value) -> bytes:
        bits = struct.unpack(">I", struct.pack(">f", float(value)))[0]
        bits = bits ^ ((1 << 32) - 1) if bits & (1 << 31) else bits ^ (1 << 31)
        return struct.pack(">I", bits)

    def read_ordered(self, data: bytes):
        bits = struct.unpack(">I", data)[0]
        bits = bits ^ (1 << 31) if bits & (1 << 31) else bits ^ ((1 << 32) - 1)
        return np.float32(struct.unpack(">f", struct.pack(">I", bits))[0])


class Char(str):
    """Single-character datatype (reference registers Character)."""

    def __new__(cls, value):
        if len(value) != 1:
            raise SerializerError("Char must be exactly one character")
        return super().__new__(cls, value)


class CharSerializer(AttributeSerializer):
    type_id = 15
    py_type = Char
    fixed_width = 4

    def write(self, value) -> bytes:
        return struct.pack(">I", ord(value))

    def read(self, data: bytes):
        return Char(chr(struct.unpack(">I", data)[0]))


# --------------------------------------------------------------------------
# Temporal types
# --------------------------------------------------------------------------

@dataclass(frozen=True, order=True)
class Instant:
    """Nanosecond-precision timestamp (reference: java.time.Instant
    registration; Python datetime caps at microseconds, so ns needs its own
    type). seconds = epoch seconds, nanos in [0, 1e9)."""

    seconds: int
    nanos: int = 0

    def __post_init__(self):
        if not (0 <= self.nanos < 1_000_000_000):
            raise SerializerError("nanos must be in [0, 1e9)")

    @staticmethod
    def of(dt: datetime) -> "Instant":
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        micros = (dt - datetime(1970, 1, 1, tzinfo=timezone.utc)) // timedelta(
            microseconds=1
        )
        sec, rem = divmod(micros, 1_000_000)
        return Instant(sec, rem * 1000)

    def to_datetime(self) -> datetime:
        return datetime(1970, 1, 1, tzinfo=timezone.utc) + timedelta(
            seconds=self.seconds, microseconds=self.nanos // 1000
        )


class InstantSerializer(AttributeSerializer):
    """[seconds:8][nanos:4]; ordered form sign-biases seconds so the whole
    12-byte encoding sorts chronologically."""

    type_id = 16
    py_type = Instant
    fixed_width = 12

    def write(self, value) -> bytes:
        return struct.pack(">qI", value.seconds, value.nanos)

    def read(self, data: bytes):
        s, n = struct.unpack(">qI", data)
        return Instant(s, n)

    def write_ordered(self, value) -> bytes:
        return struct.pack(">QI", value.seconds + (1 << 63), value.nanos)

    def read_ordered(self, data: bytes):
        s, n = struct.unpack(">QI", data)
        return Instant(s - (1 << 63), n)


class DurationSerializer(AttributeSerializer):
    type_id = 17
    py_type = timedelta
    fixed_width = 12

    def write(self, value: timedelta) -> bytes:
        micros = value // timedelta(microseconds=1)
        sec, rem = divmod(micros, 1_000_000)
        return struct.pack(">qI", sec, rem * 1000)

    def read(self, data: bytes):
        s, n = struct.unpack(">qI", data)
        return timedelta(seconds=s, microseconds=n // 1000)


class LocalDateSerializer(AttributeSerializer):
    """date as proleptic-Gregorian ordinal int32 (ordered = biased int)."""

    type_id = 18
    py_type = _date
    fixed_width = 4

    def write(self, value: _date) -> bytes:
        return struct.pack(">i", value.toordinal())

    def read(self, data: bytes):
        return _date.fromordinal(struct.unpack(">i", data)[0])

    def write_ordered(self, value) -> bytes:
        return struct.pack(">I", value.toordinal() + (1 << 31))

    def read_ordered(self, data: bytes):
        return _date.fromordinal(struct.unpack(">I", data)[0] - (1 << 31))


class LocalTimeSerializer(AttributeSerializer):
    """time-of-day as nanos-since-midnight int64 (naturally ordered)."""

    type_id = 19
    py_type = _time
    fixed_width = 8

    def write(self, value: _time) -> bytes:
        nanos = (
            (value.hour * 3600 + value.minute * 60 + value.second) * 1_000_000
            + value.microsecond
        ) * 1000
        return struct.pack(">q", nanos)

    def read(self, data: bytes):
        nanos = struct.unpack(">q", data)[0]
        micros, _ = divmod(nanos, 1000)
        sec, micro = divmod(micros, 1_000_000)
        h, rem = divmod(sec, 3600)
        m, s = divmod(rem, 60)
        return _time(h, m, s, micro)


# --------------------------------------------------------------------------
# Primitive arrays — numpy-typed (reference registers boolean[]/byte[]/
# short[]/int[]/long[]/float[]/double[]/char[]/String[] each with its own id,
# StandardSerializer.java:105-115; here each dtype gets an id and values are
# np.ndarray, which is what the OLAP path wants anyway)
# --------------------------------------------------------------------------

class NdArraySerializer(AttributeSerializer):
    """[ndim:1][dim:4 x ndim][big-endian raw data] for one fixed dtype."""

    dtype: np.dtype = None

    def write(self, value) -> bytes:
        a = np.ascontiguousarray(value, dtype=self.dtype)
        if a.ndim > 255:
            raise SerializerError("too many dimensions")
        head = struct.pack(">B", a.ndim) + b"".join(
            struct.pack(">I", d) for d in a.shape
        )
        return head + a.astype(self.dtype.newbyteorder(">")).tobytes()

    def read(self, data: bytes):
        ndim = data[0]
        shape = tuple(
            struct.unpack(">I", data[1 + 4 * i : 5 + 4 * i])[0]
            for i in range(ndim)
        )
        off = 1 + 4 * ndim
        a = np.frombuffer(data[off:], dtype=self.dtype.newbyteorder(">"))
        return a.reshape(shape).astype(self.dtype)


def _array_serializer(tid: int, np_dtype) -> NdArraySerializer:
    class _S(NdArraySerializer):
        type_id = tid
        py_type = np.ndarray
        dtype = np.dtype(np_dtype)

    _S.__name__ = f"NdArraySerializer_{np.dtype(np_dtype).name}"
    return _S()


_ARRAY_IDS = [
    (20, np.bool_), (21, np.int8), (22, np.int16), (23, np.int32),
    (24, np.int64), (25, np.float32), (26, np.float64), (27, np.uint8),
    (44, np.uint16), (45, np.uint32), (46, np.uint64), (47, np.float16),
]


# --------------------------------------------------------------------------
# Container / fallback serializers (reference: StandardSerializer.java
# registers HashMap + TraverserSet through SerializableSerializer and an
# Object fallback at id 1; the Python-idiomatic forms are a framed dict
# codec, a framed heterogeneous tuple codec, and a pickle fallback)
# --------------------------------------------------------------------------

class DictSerializer(AttributeSerializer):
    """dict with framed keys/values through the owning registry (reference:
    StandardSerializer.java:132 HashMap registration)."""

    type_id = 40
    py_type = dict

    def __init__(self, registry: "Serializer"):
        self._reg = registry

    def write_ordered(self, value) -> bytes:
        # non-canonical encoding (insertion-order-dependent) — must never
        # back a sort key or composite index row
        raise SerializerError("dict values have no order-preserving encoding")

    def write(self, value) -> bytes:
        out = [struct.pack(">I", len(value))]
        for k, v in value.items():
            for obj in (k, v):
                frame = self._reg.write_object(obj)
                out.append(struct.pack(">I", len(frame)))
                out.append(frame)
        return b"".join(out)

    def read(self, data: bytes):
        (n,) = struct.unpack(">I", data[:4])
        off = 4
        out = {}
        for _ in range(n):
            pair = []
            for _ in range(2):
                (ln,) = struct.unpack(">I", data[off:off + 4])
                off += 4
                obj, _used = self._reg.read_object(data[off:off + ln])
                pair.append(obj)
                off += ln
            out[pair[0]] = pair[1]
        return out


class TupleSerializer(AttributeSerializer):
    """Heterogeneous tuple with framed elements (covers the reference's
    boxed-array registrations — Parameter[]/char[] style fixed sequences —
    StandardSerializer.java:98-106)."""

    type_id = 41
    py_type = tuple

    def __init__(self, registry: "Serializer"):
        self._reg = registry

    def write_ordered(self, value) -> bytes:
        raise SerializerError("tuple values have no order-preserving encoding")

    def write(self, value) -> bytes:
        out = [struct.pack(">I", len(value))]
        for obj in value:
            frame = self._reg.write_object(obj)
            out.append(struct.pack(">I", len(frame)))
            out.append(frame)
        return b"".join(out)

    def read(self, data: bytes):
        (n,) = struct.unpack(">I", data[:4])
        off = 4
        out = []
        for _ in range(n):
            (ln,) = struct.unpack(">I", data[off:off + 4])
            off += 4
            obj, _used = self._reg.read_object(data[off:off + ln])
            out.append(obj)
            off += ln
        return tuple(out)


class PickledObjectSerializer(AttributeSerializer):
    """Arbitrary-object fallback via pickle (reference:
    StandardSerializer.java:78 ObjectSerializer / SerializableSerializer —
    the Kryo catch-all). SECURITY: pickle deserialization executes code;
    only registries opened with allow_pickle=True (the embedded graph's own
    cells, same trust domain as the reference's Kryo) will decode it — the
    network-facing registries (remote index server) refuse."""

    type_id = 42
    py_type = object

    def __init__(self, registry: "Serializer"):
        self._reg = registry

    def write_ordered(self, value) -> bytes:
        raise SerializerError(
            "object-fallback values have no order-preserving encoding"
        )

    def write(self, value) -> bytes:
        if not self._reg.allow_pickle:
            raise SerializerError(
                f"no serializer for {type(value).__name__} "
                "(object-pickle fallback disabled on this registry)"
            )
        import pickle

        try:
            return pickle.dumps(value, protocol=4)
        except Exception as e:
            raise SerializerError(
                f"object fallback cannot pickle {type(value).__name__}: {e}"
            ) from e

    def read(self, data: bytes):
        if not self._reg.allow_pickle:
            raise SerializerError(
                "object-pickle payload refused (allow_pickle=False registry)"
            )
        import pickle

        return pickle.loads(data)


#: importable module prefixes for ClassSerializer.read — everything else is
#: refused (a stored class name must not trigger arbitrary imports)
_CLASS_IMPORT_ALLOW = (
    "builtins", "janusgraph_tpu.", "numpy", "datetime", "decimal", "uuid",
)


def _class_path_allowed(mod: str, qual: str) -> bool:
    if "<locals>" in qual:
        return False  # function-local classes can never be re-imported
    return mod in _CLASS_IMPORT_ALLOW or any(
        mod.startswith(p) for p in _CLASS_IMPORT_ALLOW if p.endswith(".")
    )


class ClassSerializer(AttributeSerializer):
    """Python type values by dotted path (reference:
    StandardSerializer.java:126 Class registration) — schema/config cells
    that record a datatype. Write-time validation mirrors read-time: a
    class that could not be decoded later is refused BEFORE it reaches a
    cell (undecodable persisted values are data loss)."""

    type_id = 43
    py_type = type

    def write(self, value) -> bytes:
        mod, qual = value.__module__, value.__qualname__
        if not _class_path_allowed(mod, qual):
            raise SerializerError(
                f"class {mod}:{qual} not storable (module outside the "
                f"import allowlist {_CLASS_IMPORT_ALLOW} or function-local)"
            )
        return f"{mod}:{qual}".encode()

    def read(self, data: bytes):
        mod, _, qual = data.decode().partition(":")
        if not _class_path_allowed(mod, qual):
            raise SerializerError(f"class import refused for module {mod!r}")
        import importlib

        try:
            obj = importlib.import_module(mod)
            for part in qual.split("."):
                obj = getattr(obj, part)
        except (ImportError, AttributeError) as e:
            raise SerializerError(f"cannot resolve class {mod}:{qual}: {e}") from e
        if not isinstance(obj, type):
            raise SerializerError(f"{mod}:{qual} is not a type")
        return obj


class StringListSerializer(AttributeSerializer):
    """list[str] with per-item length framing (reference: String[])."""

    type_id = 28
    py_type = list  # dispatched via serializer_for's list special-case

    def write(self, value) -> bytes:
        out = [struct.pack(">I", len(value))]
        for s in value:
            raw = s.encode("utf-8")
            out.append(struct.pack(">I", len(raw)) + raw)
        return b"".join(out)

    def read(self, data: bytes):
        (n,) = struct.unpack(">I", data[:4])
        off = 4
        out = []
        for _ in range(n):
            (ln,) = struct.unpack(">I", data[off : off + 4])
            off += 4
            out.append(data[off : off + ln].decode("utf-8"))
            off += ln
        return out


# --------------------------------------------------------------------------
# Enums (reference registers each schema enum with a fixed id,
# StandardSerializer.java:90-104; user enums attach via register_enum)
# --------------------------------------------------------------------------

class EnumSerializer(AttributeSerializer):
    """One Enum class; encodes the member's ordinal position (stable as long
    as members are only appended, same contract as the reference)."""

    def __init__(self, enum_cls: Type[Enum], type_id: int):
        self.type_id = type_id
        self.py_type = enum_cls
        self._members = list(enum_cls)
        self.fixed_width = 2

    def write(self, value) -> bytes:
        return struct.pack(">H", self._members.index(value))

    def read(self, data: bytes):
        return self._members[struct.unpack(">H", data)[0]]


def _framework_enums():
    from janusgraph_tpu.core.codecs import (
        Cardinality,
        Consistency,
        Direction,
        Multiplicity,
        RelationCategory,
    )
    from janusgraph_tpu.core.config import Mutability
    from janusgraph_tpu.core.management import SchemaAction, SchemaStatus
    from janusgraph_tpu.core.txlog import LogTxStatus
    from janusgraph_tpu.indexing.provider import Mapping as IndexMapping
    from janusgraph_tpu.storage.idauthority import ConflictAvoidanceMode
    from janusgraph_tpu.util.timestamps import TimestampProviders

    return [
        (30, Direction), (31, RelationCategory), (32, Cardinality),
        (33, Multiplicity), (34, SchemaAction), (35, Mutability),
        (36, LogTxStatus), (37, IndexMapping), (48, SchemaStatus),
        (49, Consistency),
        # user-visible config enums serialized into global config
        # (reference: StandardSerializer.java:90-104 registering
        # TimestampProviders + ConflictAvoidanceMode)
        (50, TimestampProviders), (51, ConflictAvoidanceMode),
    ]


class BigInt(int):
    """Schema marker for arbitrary-precision integer property keys (the
    reference's BigInteger data type, distinct from Long). Plain ints
    outside the int64 range auto-promote to this codec on write."""


class BigIntegerSerializer(AttributeSerializer):
    """Arbitrary-precision signed integer (reference: StandardSerializer
    registers BigInteger, StandardSerializer.java:78-132). Plain form:
    minimal two's-complement big-endian. Ordered form: a length-class
    prefix byte, then sign-adjusted magnitude — longer positive magnitudes
    sort after shorter ones, longer negative magnitudes before, so byte
    order == numeric order for |v| < 2**1016."""

    type_id = 38
    py_type = BigInt  # plain int dispatches here explicitly beyond int64

    def write(self, value) -> bytes:
        length = max(1, (value.bit_length() + 8) // 8)
        return value.to_bytes(length, "big", signed=True)

    def read(self, data: bytes):
        return int.from_bytes(data, "big", signed=True)

    def write_ordered(self, value) -> bytes:
        if value == 0:
            return b"\x80"
        mag = abs(value)
        m = mag.to_bytes((mag.bit_length() + 7) // 8, "big")
        if len(m) > 0x7F:
            raise SerializerError("ordered BigInteger limited to 127 bytes")
        if value > 0:
            return bytes([0x80 + len(m)]) + m
        return bytes([0x7F - len(m)]) + bytes(255 - b for b in m)

    def read_ordered(self, data: bytes):
        b0 = data[0]
        if b0 == 0x80:
            return 0
        if b0 > 0x80:
            n = b0 - 0x80
            return int.from_bytes(data[1 : 1 + n], "big")
        n = 0x7F - b0
        mag = int.from_bytes(bytes(255 - b for b in data[1 : 1 + n]), "big")
        return -mag


class DecimalSerializer(AttributeSerializer):
    """decimal.Decimal (reference BigDecimal). Plain form: the exact string
    representation (scale-preserving round trip). Ordered form: sign class
    byte, then ordered-int64 decimal exponent and 0x01+digit bytes with a
    terminator (all complemented for negatives) — byte order == numeric
    order; decoding the ordered form yields a numerically-equal Decimal in
    minimal form (trailing zeros are not preserved there)."""

    type_id = 39
    py_type = Decimal

    def write(self, value) -> bytes:
        return str(value).encode("ascii")

    def read(self, data: bytes):
        return Decimal(data.decode("ascii"))

    def write_ordered(self, value) -> bytes:
        if value.is_nan() or value.is_infinite():
            raise SerializerError("ordered Decimal must be finite")
        if value == 0:
            return b"\x80"
        # strip trailing zeros by hand: Decimal.normalize() rounds to the
        # context precision (28 digits), conflating longer values
        sign, digits, exp = value.as_tuple()
        while len(digits) > 1 and digits[-1] == 0:
            digits = digits[:-1]
            exp += 1
        # value = 0.D1D2.. * 10**E  with D1 != 0
        e = exp + len(digits)
        ekey = struct.pack(">Q", e + (1 << 63))
        dkey = bytes(1 + d for d in digits) + b"\x00"
        if sign == 0:
            return b"\xc0" + ekey + dkey
        return b"\x40" + bytes(255 - b for b in ekey + dkey)

    def read_ordered(self, data: bytes):
        from decimal import Decimal

        b0 = data[0]
        if b0 == 0x80:
            return Decimal(0)
        body = data[1:]
        neg = b0 == 0x40
        if neg:
            body = bytes(255 - b for b in body)
        e = struct.unpack(">Q", body[:8])[0] - (1 << 63)
        digits = []
        for b in body[8:]:
            if b == 0:
                break
            digits.append(b - 1)
        d = Decimal((1 if neg else 0, tuple(digits), e - len(digits)))
        return d


#: first id available to register_enum / register for user-defined types
USER_TYPE_ID_START = 100


class Serializer:
    """The registry: type-id <-> serializer <-> python type.

    Values are framed as [type_id:2 BE][payload] so heterogeneous cells are
    self-describing (reference: StandardSerializer writeObjectNotNull)."""

    def __init__(self, allow_pickle: bool = True):
        #: whether the object-pickle fallback may encode/decode on this
        #: registry (False for network-facing registries — see
        #: PickledObjectSerializer)
        self.allow_pickle = allow_pickle
        self._by_id: Dict[int, AttributeSerializer] = {}
        self._by_type: Dict[type, AttributeSerializer] = {}
        self._array_by_dtype: Dict[np.dtype, AttributeSerializer] = {}
        for cls in (
            BooleanSerializer,
            LongSerializer,
            DoubleSerializer,
            StringSerializer,
            BytesSerializer,
            DateSerializer,
            UUIDSerializer,
            FloatListSerializer,
            GeoshapeSerializer,
            ByteSerializer,
            ShortSerializer,
            IntSerializer,
            NumpyLongSerializer,
            FloatSerializer,
            CharSerializer,
            InstantSerializer,
            DurationSerializer,
            LocalDateSerializer,
            LocalTimeSerializer,
            StringListSerializer,
            BigIntegerSerializer,
            DecimalSerializer,
            ClassSerializer,
        ):
            self.register(cls())
        for cls in (DictSerializer, TupleSerializer, PickledObjectSerializer):
            self.register(cls(self))
        for tid, dt in _ARRAY_IDS:
            ser = _array_serializer(tid, dt)
            self._by_id[tid] = ser
            self._array_by_dtype[np.dtype(dt)] = ser
        self._by_type[np.ndarray] = self._array_by_dtype[np.dtype(np.float64)]
        for tid, enum_cls in _framework_enums():
            self.register_enum(enum_cls, tid)

    def register_enum(self, enum_cls: Type[Enum], type_id: int) -> None:
        """Attach an Enum datatype (user enums: type_id >= USER_TYPE_ID_START)."""
        self.register(EnumSerializer(enum_cls, type_id))

    def register(self, ser: AttributeSerializer) -> None:
        if ser.type_id in self._by_id:
            raise SerializerError(f"duplicate serializer id {ser.type_id}")
        if ser.type_id >= 0xFFFF:
            # 0xFFFF is the property-cell META marker (codecs._META_MARKER)
            # — a value frame starting with it would misparse as metas
            raise SerializerError(
                f"serializer id {ser.type_id} reserved (>= 0xFFFF)"
            )
        self._by_id[ser.type_id] = ser
        # first registration wins the python-type slot (list maps to
        # FloatListSerializer; StringListSerializer dispatches by content)
        self._by_type.setdefault(ser.py_type, ser)

    def serializer_for(self, value) -> AttributeSerializer:
        # numpy arrays dispatch on dtype (one id per element type, mirroring
        # the reference's per-primitive array registrations)
        if isinstance(value, np.ndarray):
            ser = self._array_by_dtype.get(value.dtype)
            if ser is None:
                raise SerializerError(f"no array serializer for dtype {value.dtype}")
            return ser
        # lists: numeric lists keep the legacy FloatList encoding; string
        # lists use the String[] analogue
        if isinstance(value, list):
            if value and all(isinstance(x, str) for x in value):
                return self._by_id[StringListSerializer.type_id]
            return self._by_id[FloatListSerializer.type_id]
        # ints beyond 64 bits promote to the BigInteger codec (the plain
        # int slot belongs to LongSerializer, whose struct.pack would raise)
        if (
            isinstance(value, int)
            and not isinstance(value, bool)
            and not (-(1 << 63) <= value < (1 << 63))
        ):
            return self._by_id[BigIntegerSerializer.type_id]
        # bool is a subclass of int: check exact type first, then walk MRO
        ser = self._by_type.get(type(value))
        if ser is not None:
            return ser
        for t, s in self._by_type.items():
            if isinstance(value, t) and not (
                t is int and isinstance(value, bool)
            ):
                return s
        raise SerializerError(f"no serializer for {type(value).__name__}")

    def serializer_for_type(self, py_type: type) -> AttributeSerializer:
        ser = self._by_type.get(py_type)
        if ser is None:
            raise SerializerError(f"no serializer for type {py_type.__name__}")
        return ser

    # -- framed object encoding --------------------------------------------
    def write_object(self, value) -> bytes:
        ser = self.serializer_for(value)
        return struct.pack(">H", ser.type_id) + ser.write(value)

    def read_object(self, data: bytes) -> Tuple[Any, int]:
        """Decode a framed value; returns (value, bytes_consumed). Only
        fixed-width payloads can be length-inferred mid-stream; variable-width
        payloads must be the tail of `data`."""
        (tid,) = struct.unpack(">H", data[:2])
        ser = self._by_id.get(tid)
        if ser is None:
            raise SerializerError(f"unknown serializer id {tid}")
        if ser.fixed_width is not None:
            end = 2 + ser.fixed_width
            return ser.read(data[2:end]), end
        return ser.read(data[2:]), len(data)

    # -- order-preserving encoding (sort keys / index keys) ----------------
    def write_ordered(self, value) -> bytes:
        ser = self.serializer_for(value)
        return ser.write_ordered(value)

    def data_type_id(self, py_type: type) -> int:
        return self.serializer_for_type(py_type).type_id

    def type_for_id(self, tid: int) -> type:
        ser = self._by_id.get(tid)
        if ser is None:
            raise SerializerError(f"unknown serializer id {tid}")
        return ser.py_type
