"""Attribute serializer registry.

Capability parity with the reference's serializer stack
(reference: graphdb/database/serialize/StandardSerializer.java:78-132
fixed-id registrations; serialize/attribute/*): a registry of binary
serializers keyed by a stable small integer id, with an *order-preserving*
mode used for sort keys and composite-index keys (byte-wise lexicographic
order of the encoding == natural order of the value).

Own design notes (not a port): encodings are fixed-width big-endian where
possible so the OLAP bulk loader can decode property columns with vectorized
numpy views instead of per-value Python.
"""

from __future__ import annotations

import struct
import uuid as _uuid
from datetime import datetime, timedelta, timezone
from typing import Any, Dict, Optional, Tuple, Type

from janusgraph_tpu.exceptions import JanusGraphTPUError


class SerializerError(JanusGraphTPUError):
    pass


class AttributeSerializer:
    """One datatype's binary codec. Subclasses set `type_id` and `py_type`."""

    type_id: int = -1
    py_type: type = object
    #: fixed encoded byte width, or None if variable
    fixed_width: Optional[int] = None

    def write(self, value) -> bytes:
        raise NotImplementedError

    def read(self, data: bytes):
        raise NotImplementedError

    # order-preserving variants default to the plain encoding when the plain
    # encoding already sorts correctly; override otherwise.
    def write_ordered(self, value) -> bytes:
        return self.write(value)

    def read_ordered(self, data: bytes):
        return self.read(data)


class BooleanSerializer(AttributeSerializer):
    type_id = 1
    py_type = bool
    fixed_width = 1

    def write(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def read(self, data: bytes):
        return data[0] != 0


class LongSerializer(AttributeSerializer):
    """Signed 64-bit. Ordered form flips the sign bit so byte order == numeric
    order (two's-complement big-endian sorts negatives after positives
    otherwise)."""

    type_id = 2
    py_type = int
    fixed_width = 8

    def write(self, value) -> bytes:
        return struct.pack(">q", value)

    def read(self, data: bytes):
        return struct.unpack(">q", data)[0]

    def write_ordered(self, value) -> bytes:
        # struct raises on out-of-range, matching the plain write() path
        return struct.pack(">Q", value + (1 << 63))

    def read_ordered(self, data: bytes):
        return struct.unpack(">Q", data)[0] - (1 << 63)


class DoubleSerializer(AttributeSerializer):
    """IEEE-754 double. Ordered form uses the total-order trick: flip all bits
    of negatives, flip only the sign bit of non-negatives."""

    type_id = 3
    py_type = float
    fixed_width = 8

    def write(self, value) -> bytes:
        return struct.pack(">d", value)

    def read(self, data: bytes):
        return struct.unpack(">d", data)[0]

    def write_ordered(self, value) -> bytes:
        bits = struct.unpack(">Q", struct.pack(">d", value))[0]
        if bits & (1 << 63):
            bits ^= (1 << 64) - 1
        else:
            bits ^= 1 << 63
        return struct.pack(">Q", bits)

    def read_ordered(self, data: bytes):
        bits = struct.unpack(">Q", data)[0]
        if bits & (1 << 63):
            bits ^= 1 << 63
        else:
            bits ^= (1 << 64) - 1
        return struct.unpack(">d", struct.pack(">Q", bits))[0]


class StringSerializer(AttributeSerializer):
    """UTF-8. Ordered form appends a NUL terminator; embedded NULs are
    rejected in ordered mode so prefix containment can't corrupt ordering
    (reference counterpart compresses — we favor vectorizable simplicity)."""

    type_id = 4
    py_type = str

    def write(self, value) -> bytes:
        return value.encode("utf-8")

    def read(self, data: bytes):
        return data.decode("utf-8")

    def write_ordered(self, value) -> bytes:
        raw = value.encode("utf-8")
        if b"\x00" in raw:
            raise SerializerError("NUL not allowed in ordered (sort-key) strings")
        return raw + b"\x00"

    def read_ordered(self, data: bytes):
        if not data.endswith(b"\x00"):
            raise SerializerError("malformed ordered string")
        return data[:-1].decode("utf-8")


class BytesSerializer(AttributeSerializer):
    type_id = 5
    py_type = bytes

    def write(self, value) -> bytes:
        return bytes(value)

    def read(self, data: bytes):
        return bytes(data)


class DateSerializer(AttributeSerializer):
    """UTC datetime as epoch-micros int64 (ordered like LongSerializer)."""

    type_id = 6
    py_type = datetime
    fixed_width = 8

    _EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)

    def _to_micros(self, value: datetime) -> int:
        if value.tzinfo is None:
            value = value.replace(tzinfo=timezone.utc)
        # integer arithmetic: float timestamps lose microseconds far from epoch
        return (value - self._EPOCH) // timedelta(microseconds=1)

    def _from_micros(self, micros: int) -> datetime:
        return self._EPOCH + timedelta(microseconds=micros)

    def write(self, value) -> bytes:
        return struct.pack(">q", self._to_micros(value))

    def read(self, data: bytes):
        return self._from_micros(struct.unpack(">q", data)[0])

    def write_ordered(self, value) -> bytes:
        return LongSerializer().write_ordered(self._to_micros(value))

    def read_ordered(self, data: bytes):
        return self._from_micros(LongSerializer().read_ordered(data))


class UUIDSerializer(AttributeSerializer):
    type_id = 7
    py_type = _uuid.UUID
    fixed_width = 16

    def write(self, value) -> bytes:
        return value.bytes

    def read(self, data: bytes):
        return _uuid.UUID(bytes=bytes(data))


class FloatListSerializer(AttributeSerializer):
    """list[float] — the OLAP compute-property carrier (e.g. pagerank vectors)."""

    type_id = 8
    py_type = list

    def write(self, value) -> bytes:
        return struct.pack(f">{len(value)}d", *value)

    def read(self, data: bytes):
        n = len(data) // 8
        return list(struct.unpack(f">{n}d", data))


from janusgraph_tpu.core.predicates import Geoshape


def GeoshapePoint(lat: float, lon: float) -> Geoshape:
    """Compat shim: the original minimal point type, now the full Geoshape
    vocabulary (reference: core/attribute/Geoshape.java:623)."""
    return Geoshape.point(lat, lon)


class GeoshapeSerializer(AttributeSerializer):
    """Kind-tagged binary: 0x01 point[2d], 0x02 circle[3d], 0x03 box[4d],
    0x04 polygon[count:2][2d each] (reference: Geoshape.GeoShapeSerializer
    binary codec)."""

    type_id = 9
    py_type = Geoshape

    def write(self, value) -> bytes:
        if value.kind == "Point":
            return b"\x01" + struct.pack(">dd", value.lat, value.lon)
        if value.kind == "Circle":
            return b"\x02" + struct.pack(
                ">ddd", value.lat, value.lon, value.radius_km
            )
        if value.kind == "Box":
            (slat, slon), (nlat, nlon) = value.coords
            return b"\x03" + struct.pack(">dddd", slat, slon, nlat, nlon)
        out = [b"\x04", struct.pack(">H", len(value.coords))]
        for la, lo in value.coords:
            out.append(struct.pack(">dd", la, lo))
        return b"".join(out)

    def read(self, data: bytes):
        kind = data[0]
        if kind == 1:
            return Geoshape.point(*struct.unpack(">dd", data[1:17]))
        if kind == 2:
            return Geoshape.circle(*struct.unpack(">ddd", data[1:25]))
        if kind == 3:
            return Geoshape.box(*struct.unpack(">dddd", data[1:33]))
        (n,) = struct.unpack(">H", data[1:3])
        pts = [
            struct.unpack(">dd", data[3 + 16 * i : 19 + 16 * i]) for i in range(n)
        ]
        return Geoshape.polygon(pts)


class Serializer:
    """The registry: type-id <-> serializer <-> python type.

    Values are framed as [type_id:2 BE][payload] so heterogeneous cells are
    self-describing (reference: StandardSerializer writeObjectNotNull)."""

    def __init__(self):
        self._by_id: Dict[int, AttributeSerializer] = {}
        self._by_type: Dict[type, AttributeSerializer] = {}
        for cls in (
            BooleanSerializer,
            LongSerializer,
            DoubleSerializer,
            StringSerializer,
            BytesSerializer,
            DateSerializer,
            UUIDSerializer,
            FloatListSerializer,
            GeoshapeSerializer,
        ):
            self.register(cls())

    def register(self, ser: AttributeSerializer) -> None:
        if ser.type_id in self._by_id:
            raise SerializerError(f"duplicate serializer id {ser.type_id}")
        self._by_id[ser.type_id] = ser
        self._by_type[ser.py_type] = ser

    def serializer_for(self, value) -> AttributeSerializer:
        # bool is a subclass of int: check exact type first, then walk MRO
        ser = self._by_type.get(type(value))
        if ser is not None:
            return ser
        for t, s in self._by_type.items():
            if isinstance(value, t) and not (
                t is int and isinstance(value, bool)
            ):
                return s
        raise SerializerError(f"no serializer for {type(value).__name__}")

    def serializer_for_type(self, py_type: type) -> AttributeSerializer:
        ser = self._by_type.get(py_type)
        if ser is None:
            raise SerializerError(f"no serializer for type {py_type.__name__}")
        return ser

    # -- framed object encoding --------------------------------------------
    def write_object(self, value) -> bytes:
        ser = self.serializer_for(value)
        return struct.pack(">H", ser.type_id) + ser.write(value)

    def read_object(self, data: bytes) -> Tuple[Any, int]:
        """Decode a framed value; returns (value, bytes_consumed). Only
        fixed-width payloads can be length-inferred mid-stream; variable-width
        payloads must be the tail of `data`."""
        (tid,) = struct.unpack(">H", data[:2])
        ser = self._by_id.get(tid)
        if ser is None:
            raise SerializerError(f"unknown serializer id {tid}")
        if ser.fixed_width is not None:
            end = 2 + ser.fixed_width
            return ser.read(data[2:end]), end
        return ser.read(data[2:]), len(data)

    # -- order-preserving encoding (sort keys / index keys) ----------------
    def write_ordered(self, value) -> bytes:
        ser = self.serializer_for(value)
        return ser.write_ordered(value)

    def data_type_id(self, py_type: type) -> int:
        return self.serializer_for_type(py_type).type_id

    def type_for_id(self, tid: int) -> type:
        ser = self._by_id.get(tid)
        if ser is None:
            raise SerializerError(f"unknown serializer id {tid}")
        return ser.py_type
