"""Gremlin-style fluent traversal DSL.

Capability parity with the reference's OLTP query path — not TinkerPop's JVM
machinery, but the same step vocabulary and, crucially, the same two
optimizations the reference registers as traversal strategies
(reference: graphdb/tinkerpop/optimize/strategy/JanusGraphStepStrategy.java —
fold leading has() chains into one index-backed start step;
JanusGraphLocalQueryOptimizerStrategy.java — batch vertex expansion through
multiQuery prefetch):

- `g.V().has('name', 'x')` folds its has-chain, matches it against the
  registered composite indexes, and starts from an index lookup instead of a
  full scan when every index key is covered by equality conditions.
- `out()/in_()/both()/outE()/...` prefetch the needed slices for ALL current
  traversers with one batched multi-query before expanding.

Execution model is batch-at-a-time (each step maps a list of traversers to
the next list), which matches both the multi-query optimization and the
batch thinking of the TPU OLAP path.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, List, Optional, Sequence

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.elements import Edge, Vertex, VertexProperty
from janusgraph_tpu.core.predicates import Cmp, Geo, Text
from janusgraph_tpu.core.schema import IndexDefinition
from janusgraph_tpu.exceptions import QueryError


class P:
    """Predicate (reference vocabulary: core/attribute/Cmp.java, Text.java,
    Geo.java). Carries the structured (predicate, condition) pair so index
    selection can push it down to composite rows or a mixed IndexProvider."""

    def __init__(
        self,
        test: Callable[[object], bool],
        label: str,
        eq_value=None,
        predicate=None,
        condition=None,
    ):
        self.test = test
        self.label = label
        #: set when the predicate is a plain equality — index-foldable
        self.eq_value = eq_value
        #: structured predicate for mixed-index pushdown (None = opaque)
        self.predicate = predicate
        self.condition = condition

    def __repr__(self):
        return f"P.{self.label}"

    @staticmethod
    def _of(pred, v, label) -> "P":
        return P(
            lambda x: pred.evaluate(x, v), label, predicate=pred, condition=v
        )

    @staticmethod
    def eq(v) -> "P":
        return P(
            lambda x: x == v,
            f"eq({v!r})",
            eq_value=v,
            predicate=Cmp.EQUAL,
            condition=v,
        )

    @staticmethod
    def neq(v) -> "P":
        return P(
            lambda x: x != v, f"neq({v!r})", predicate=Cmp.NOT_EQUAL, condition=v
        )

    @staticmethod
    def gt(v) -> "P":
        return P(
            lambda x: x is not None and x > v,
            f"gt({v!r})",
            predicate=Cmp.GREATER_THAN,
            condition=v,
        )

    @staticmethod
    def gte(v) -> "P":
        return P(
            lambda x: x is not None and x >= v,
            f"gte({v!r})",
            predicate=Cmp.GREATER_THAN_EQUAL,
            condition=v,
        )

    @staticmethod
    def lt(v) -> "P":
        return P(
            lambda x: x is not None and x < v,
            f"lt({v!r})",
            predicate=Cmp.LESS_THAN,
            condition=v,
        )

    @staticmethod
    def lte(v) -> "P":
        return P(
            lambda x: x is not None and x <= v,
            f"lte({v!r})",
            predicate=Cmp.LESS_THAN_EQUAL,
            condition=v,
        )

    @staticmethod
    def within(*vs) -> "P":
        s = set(vs)
        return P(lambda x: x in s, f"within{tuple(vs)!r}")

    @staticmethod
    def without(*vs) -> "P":
        s = set(vs)
        return P(lambda x: x not in s, f"without{tuple(vs)!r}")

    @staticmethod
    def between(lo, hi) -> "P":
        return P(lambda x: x is not None and lo <= x < hi, f"between({lo!r},{hi!r})")

    # ---- full-text predicates (reference: attribute/Text.java) ----
    @staticmethod
    def text_contains(v) -> "P":
        return P._of(Text.CONTAINS, v, f"textContains({v!r})")

    @staticmethod
    def text_contains_prefix(v) -> "P":
        return P._of(Text.CONTAINS_PREFIX, v, f"textContainsPrefix({v!r})")

    @staticmethod
    def text_contains_regex(v) -> "P":
        return P._of(Text.CONTAINS_REGEX, v, f"textContainsRegex({v!r})")

    @staticmethod
    def text_contains_fuzzy(v) -> "P":
        return P._of(Text.CONTAINS_FUZZY, v, f"textContainsFuzzy({v!r})")

    @staticmethod
    def text_contains_phrase(v) -> "P":
        return P._of(Text.CONTAINS_PHRASE, v, f"textContainsPhrase({v!r})")

    @staticmethod
    def text_prefix(v) -> "P":
        return P._of(Text.PREFIX, v, f"textPrefix({v!r})")

    @staticmethod
    def text_regex(v) -> "P":
        return P._of(Text.REGEX, v, f"textRegex({v!r})")

    @staticmethod
    def text_fuzzy(v) -> "P":
        return P._of(Text.FUZZY, v, f"textFuzzy({v!r})")

    # ---- geo predicates (reference: attribute/Geo.java) ----
    @staticmethod
    def geo_intersect(shape) -> "P":
        return P._of(Geo.INTERSECT, shape, f"geoIntersect({shape!r})")

    @staticmethod
    def geo_within(shape) -> "P":
        return P._of(Geo.WITHIN, shape, f"geoWithin({shape!r})")

    @staticmethod
    def geo_disjoint(shape) -> "P":
        return P._of(Geo.DISJOINT, shape, f"geoDisjoint({shape!r})")

    @staticmethod
    def geo_contains(shape) -> "P":
        return P._of(Geo.CONTAINS, shape, f"geoContains({shape!r})")


class Traverser:
    """One unit of traversal state: the current object plus the vertex it was
    reached from (needed by otherV) — a minimal path memory."""

    __slots__ = ("obj", "prev")

    def __init__(self, obj, prev=None):
        self.obj = obj
        self.prev = prev


class GraphTraversalSource:
    def __init__(self, graph, tx=None):
        self.graph = graph
        self.tx = tx or graph.new_transaction()

    def V(self, *ids) -> "GraphTraversal":
        return GraphTraversal(self, _start_vertices(self, ids))

    def E(self) -> "GraphTraversal":
        return GraphTraversal(self, _start_edges(self))

    def add_v(self, label: Optional[str] = None, **props) -> Vertex:
        return self.tx.add_vertex(label, **props)

    def add_e(self, out_v: Vertex, label: str, in_v: Vertex, **props) -> Edge:
        return self.tx.add_edge(out_v, label, in_v, **props)

    def commit(self) -> None:
        self.tx.commit()
        self.tx = self.graph.new_transaction()

    def rollback(self) -> None:
        self.tx.rollback()
        self.tx = self.graph.new_transaction()


# ---------------------------------------------------------------- start steps
class _start_vertices:
    def __init__(self, source: GraphTraversalSource, ids):
        self.source = source
        self.ids = ids
        #: filled at run(): how the start step resolved (for .profile())
        self.plan: dict = {}

    def run(self, has_conditions) -> List[Traverser]:
        tx = self.source.tx
        if self.ids:
            self.plan = {"access": "ids"}
            out = []
            for i in self.ids:
                v = tx.get_vertex(i.id if isinstance(i, Vertex) else i)
                if v is not None:
                    out.append(Traverser(v))
            return _apply_has(out, has_conditions, tx)
        # index folding: find a composite index fully covered by eq conditions
        eqs = {
            key: p.eq_value
            for key, p in has_conditions
            if p.eq_value is not None and key is not None
        }
        # label equality (if any) gates label-constrained indexes
        label_eq = None
        for key, p in has_conditions:
            if key is None and p.eq_value is not None:
                label_eq = p.eq_value
        idx = _select_index(self.source.graph, eqs, label_eq)
        if idx is not None:
            self.plan = {"access": "composite-index", "index": idx.name}
            names = [
                self.source.graph.schema_cache.get_by_id(k).name
                for k in idx.key_ids
            ]
            vids = self.source.graph.index_lookup(
                tx, idx.name, [eqs[n] for n in names]
            )
            return _index_hits_with_tx_overlay(tx, vids, has_conditions)
        # mixed-index folding: push supported predicate conditions down to an
        # IndexProvider (reference: GraphCentricQueryBuilder index selection
        # falling back from composite to mixed indexes)
        hit = _select_mixed_index(self.source.graph, has_conditions, label_eq)
        if hit is not None:
            midx, covered = hit
            self.plan = {
                "access": "mixed-index",
                "index": midx.name,
                "conditions_pushed": len(covered),
            }
            vids = self.source.graph.mixed_index_query(tx, midx, covered)
            return _index_hits_with_tx_overlay(tx, vids, has_conditions)
        # full scan (the reference warns here too)
        self.plan = {"access": "full-scan"}
        return _apply_has([Traverser(v) for v in tx.vertices()], has_conditions, tx)


class _start_edges:
    def __init__(self, source: GraphTraversalSource):
        self.source = source

    def run(self, has_conditions) -> List[Traverser]:
        tx = self.source.tx
        out, seen = [], set()
        for v in tx.vertices():
            for e in tx.get_edges(v, Direction.OUT, ()):
                if e.id not in seen:
                    seen.add(e.id)
                    out.append(Traverser(e))
        return _apply_has(out, has_conditions, tx)


def _index_hits_with_tx_overlay(tx, vids, has_conditions) -> List[Traverser]:
    """Committed index hits can't see this tx's writes: add tx-created
    vertices AND loaded vertices whose properties changed in-tx; _apply_has
    then re-checks every condition on current values."""
    out = [Traverser(v) for vid in vids if (v := tx.get_vertex(vid))]
    dirty = {
        vid
        for vid, rels in tx._added.items()
        if any(isinstance(r, VertexProperty) for r in rels)
    }
    dirty.update(
        r.vertex.id for r in tx._deleted if isinstance(r, VertexProperty)
    )
    out.extend(
        Traverser(v)
        for v in tx._vertex_cache.values()
        if not v.is_removed and (v.is_new or v.id in dirty)
    )
    return _apply_has(_dedup(out), has_conditions, tx)


def _select_mixed_index(graph, has_conditions, label_eq=None):
    """Pick the mixed index covering the most pushable conditions; returns
    (index, [(key, predicate, condition), ...]) or None."""
    best = None
    for idx in graph.indexes.values():
        if not idx.mixed or idx.status != "ENABLED":
            continue
        if idx.label_constraint is not None and idx.label_constraint != label_eq:
            continue
        provider = graph.index_providers.get(idx.backing)
        if provider is None:
            continue
        fields = graph.mixed_index_fields(idx)
        covered = []
        for key, p in has_conditions:
            if key is None or p.predicate is None or key not in fields:
                continue
            _kid, info = fields[key]
            if provider.supports(info, p.predicate):
                covered.append((key, p.predicate, p.condition))
        if covered and (best is None or len(covered) > len(best[1])):
            best = (idx, covered)
    return best


def _select_index(graph, eqs: dict, label_eq=None) -> Optional[IndexDefinition]:
    best = None
    for idx in graph.indexes.values():
        if idx.mixed or idx.status != "ENABLED":
            continue  # exact-row lookups on ENABLED composite indexes only
        # a label-constrained index only covers vertices of that label: it is
        # usable only when the query pins the label to exactly that value
        if idx.label_constraint is not None and idx.label_constraint != label_eq:
            continue
        names = []
        for k in idx.key_ids:
            el = graph.schema_cache.get_by_id(k)
            if el is None:
                break
            names.append(el.name)
        if len(names) != len(idx.key_ids):
            continue
        if all(n in eqs for n in names):
            if best is None or len(idx.key_ids) > len(best.key_ids):
                best = idx
    return best


def _element_value(t: Traverser, key: str, tx):
    obj = t.obj
    if isinstance(obj, Vertex):
        return obj.value(key)
    if isinstance(obj, Edge):
        return obj.value(key)
    if isinstance(obj, VertexProperty):
        return obj.value if obj.key == key else None
    return None


def _apply_has(ts: List[Traverser], conditions, tx) -> List[Traverser]:
    out = ts
    for key, p in conditions:
        if key is None:  # label condition
            out = [t for t in out if p.test(_label_of(t.obj))]
        else:
            out = [t for t in out if p.test(_element_value(t, key, tx))]
    return out


def _label_of(obj):
    if isinstance(obj, (Vertex, Edge)):
        return obj.label
    if isinstance(obj, VertexProperty):
        return obj.key
    return None


def _dedup(ts: List[Traverser]) -> List[Traverser]:
    seen, out = set(), []
    for t in ts:
        k = t.obj if not isinstance(t.obj, (Vertex, Edge)) else t.obj.id
        try:
            if k in seen:
                continue
            seen.add(k)
        except TypeError:
            pass  # unhashable values are kept
        out.append(t)
    return out


# ------------------------------------------------------------------ traversal
class GraphTraversal:
    def __init__(self, source: GraphTraversalSource, start):
        self.source = source
        self.tx = source.tx
        self._start = start
        self._pre_has: List = []  # foldable leading has-conditions
        self._steps: List[Callable[[List[Traverser]], List[Traverser]]] = []
        self._folding = True  # still collecting leading has() steps

    # -- filters ------------------------------------------------------------
    def has(self, key: str, value=None) -> "GraphTraversal":
        if value is None:
            p = P(lambda x: x is not None, f"exists({key})")
        elif isinstance(value, P):
            p = value
        else:
            p = P.eq(value)
        if self._folding:
            self._pre_has.append((key, p))
        else:
            tx = self.tx
            self._add(
                lambda ts: [t for t in ts if p.test(_element_value(t, key, tx))],
                name=f"has({key})",
            )
        return self

    def has_label(self, *labels: str) -> "GraphTraversal":
        # single label folds as an equality so label-constrained indexes apply
        p = P.eq(labels[0]) if len(labels) == 1 else P.within(*labels)
        if self._folding:
            self._pre_has.append((None, p))
        else:
            self._add(
                lambda ts: [t for t in ts if p.test(_label_of(t.obj))],
                name="hasLabel",
            )
        return self

    def has_id(self, *ids: int) -> "GraphTraversal":
        idset = set(ids)
        self._add(lambda ts: [t for t in ts if getattr(t.obj, "id", None) in idset])
        return self

    def filter_(self, fn: Callable[[object], bool]) -> "GraphTraversal":
        self._add(lambda ts: [t for t in ts if fn(t.obj)])
        return self

    def _add(self, step, name: Optional[str] = None) -> None:
        self._folding = False
        # label for .profile(): the public step method that registered it
        import sys

        step._label = name or sys._getframe(1).f_code.co_name
        self._steps.append(step)

    # -- vertex expansion (batched via prefetch) -----------------------------
    def out(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.OUT, labels, to_vertex=True)

    def in_(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.IN, labels, to_vertex=True)

    def both(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.BOTH, labels, to_vertex=True)

    def out_e(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.OUT, labels, to_vertex=False)

    def in_e(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.IN, labels, to_vertex=False)

    def both_e(self, *labels: str) -> "GraphTraversal":
        return self._expand(Direction.BOTH, labels, to_vertex=False)

    def _expand(self, direction, labels, to_vertex) -> "GraphTraversal":
        tx = self.tx

        def step(ts: List[Traverser]) -> List[Traverser]:
            vs = [t.obj for t in ts if isinstance(t.obj, Vertex)]
            tx.prefetch(vs, direction, labels)  # the multiQuery batch
            out: List[Traverser] = []
            for t in ts:
                v = t.obj
                if not isinstance(v, Vertex):
                    continue
                for e in tx.get_edges(v, direction, labels):
                    if to_vertex:
                        out.append(Traverser(e.other(v), prev=v))
                    else:
                        out.append(Traverser(e, prev=v))
            return out

        kind = {Direction.OUT: "out", Direction.IN: "in", Direction.BOTH: "both"}[
            direction
        ]
        suffix = ("" if to_vertex else "E") + (
            f"({','.join(labels)})" if labels else "()"
        )
        self._add(step, name=kind + suffix)
        return self

    def out_v(self) -> "GraphTraversal":
        self._add(
            lambda ts: [
                Traverser(t.obj.out_vertex) for t in ts if isinstance(t.obj, Edge)
            ]
        )
        return self

    def in_v(self) -> "GraphTraversal":
        self._add(
            lambda ts: [
                Traverser(t.obj.in_vertex) for t in ts if isinstance(t.obj, Edge)
            ]
        )
        return self

    def other_v(self) -> "GraphTraversal":
        def step(ts):
            out = []
            for t in ts:
                if isinstance(t.obj, Edge) and t.prev is not None:
                    out.append(Traverser(t.obj.other(t.prev), prev=t.prev))
            return out

        self._add(step)
        return self

    def both_v(self) -> "GraphTraversal":
        def step(ts):
            out = []
            for t in ts:
                if isinstance(t.obj, Edge):
                    out.append(Traverser(t.obj.out_vertex))
                    out.append(Traverser(t.obj.in_vertex))
            return out

        self._add(step)
        return self

    # -- projections ---------------------------------------------------------
    def values(self, *keys: str) -> "GraphTraversal":
        tx = self.tx

        def step(ts):
            out = []
            for t in ts:
                if isinstance(t.obj, Vertex):
                    props = tx.get_properties(t.obj, *keys)
                    out.extend(Traverser(p.value, prev=t.prev) for p in props)
                elif isinstance(t.obj, Edge):
                    pv = t.obj.property_values()
                    for k, v in pv.items():
                        if not keys or k in keys:
                            out.append(Traverser(v, prev=t.prev))
            return out

        self._add(step)
        return self

    def properties(self, *keys: str) -> "GraphTraversal":
        tx = self.tx
        self._add(
            lambda ts: [
                Traverser(p, prev=t.prev)
                for t in ts
                if isinstance(t.obj, Vertex)
                for p in tx.get_properties(t.obj, *keys)
            ]
        )
        return self

    def value_map(self, *keys: str) -> "GraphTraversal":
        tx = self.tx

        def step(ts):
            out = []
            for t in ts:
                if isinstance(t.obj, Vertex):
                    m = {}
                    for p in tx.get_properties(t.obj, *keys):
                        m.setdefault(p.key, []).append(p.value)
                    out.append(Traverser(m, prev=t.prev))
                elif isinstance(t.obj, Edge):
                    out.append(Traverser(t.obj.property_values(), prev=t.prev))
            return out

        self._add(step)
        return self

    def id_(self) -> "GraphTraversal":
        self._add(lambda ts: [Traverser(t.obj.id, prev=t.prev) for t in ts])
        return self

    def label_(self) -> "GraphTraversal":
        self._add(lambda ts: [Traverser(_label_of(t.obj), prev=t.prev) for t in ts])
        return self

    # -- collection/order/slicing -------------------------------------------
    def dedup(self) -> "GraphTraversal":
        self._add(_dedup)
        return self

    def limit(self, n: int) -> "GraphTraversal":
        self._add(lambda ts: ts[:n])
        return self

    def range_(self, lo: int, hi: int) -> "GraphTraversal":
        self._add(lambda ts: ts[lo:hi])
        return self

    def order(self, key: Optional[str] = None, reverse: bool = False) -> "GraphTraversal":
        tx = self.tx

        def step(ts):
            if key is None:
                return sorted(ts, key=lambda t: t.obj, reverse=reverse)
            return sorted(
                ts,
                key=lambda t: (_element_value(t, key, tx) is None,
                               _element_value(t, key, tx)),
                reverse=reverse,
            )

        self._add(step)
        return self

    def repeat(self, body: Callable[["GraphTraversal"], "GraphTraversal"], times: int) -> "GraphTraversal":
        """t.repeat(lambda t: t.out('knows'), times=3)"""
        for _ in range(times):
            body(self)
        return self

    # -- aggregation ---------------------------------------------------------
    def count(self) -> int:
        return len(self._execute())

    def sum_(self):
        return sum(t.obj for t in self._execute())

    def max_(self):
        vals = [t.obj for t in self._execute()]
        return max(vals) if vals else None

    def min_(self):
        vals = [t.obj for t in self._execute()]
        return min(vals) if vals else None

    def mean_(self):
        vals = [t.obj for t in self._execute()]
        return sum(vals) / len(vals) if vals else None

    def group_count(self, key: Optional[str] = None) -> dict:
        tx = self.tx
        ts = self._execute()
        if key is None:
            return dict(Counter(t.obj for t in ts))
        return dict(Counter(_element_value(t, key, tx) for t in ts))

    # -- terminals -----------------------------------------------------------
    def _execute(self, observe=None) -> List[Traverser]:
        """One execution path for plain runs and .profile(): `observe` wraps
        every stage invocation (label, fn, input) -> output."""
        run = observe if observe is not None else (lambda _label, fn, ts: fn(ts))
        ts = run("start", lambda _: self._start.run(self._pre_has), None)
        for step in self._steps:
            ts = run(getattr(step, "_label", "step"), step, ts)
        return ts

    def profile(self):
        """Execute with per-step timing and plan annotations (reference:
        Gremlin .profile() → QueryProfiler via TP3ProfileWrapper.java;
        annotations mirror SimpleQueryProfiler's condition/index notes)."""
        from janusgraph_tpu.core.profile import QueryProfiler, TraversalMetrics

        root = QueryProfiler("traversal")

        def observe(label, fn, ts):
            p = root.add_nested(label)
            with p:
                out = fn(ts)
            p.annotate("traversers", len(out))
            if label == "start":
                if self._pre_has:
                    p.annotate(
                        "conditions",
                        [f"{k or 'label'}:{pr.label}" for k, pr in self._pre_has],
                    )
                for k, v in getattr(self._start, "plan", {}).items():
                    p.annotate(k, v)
            return out

        with root:
            ts = self._execute(observe)
        return TraversalMetrics(root, [t.obj for t in ts])

    def to_list(self) -> List[object]:
        return [t.obj for t in self._execute()]

    def to_set(self) -> set:
        return set(self.to_list())

    def next(self):
        res = self._execute()
        if not res:
            raise QueryError("traversal returned no results")
        return res[0].obj

    def try_next(self):
        res = self._execute()
        return res[0].obj if res else None

    def iterate(self) -> None:
        self._execute()

    def __iter__(self):
        return iter(self.to_list())
